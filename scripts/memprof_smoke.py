#!/usr/bin/env python
"""Resource-observatory smoke: forced leak -> page -> recover, for real.

One short REAL run of the memwatch + pyprof stack against an in-process
server (the perf_gate harness pattern: port 0, manual history ticks), with
a disk leak injected at a known rate into a watched spool directory:

  baseline   ticks with no leak: memwatch samples flow into the history
             store, ``mem_leak_trend`` / ``resource_exhaustion`` stay ok.
  leak       a fixed chunk appended to the watched dir every tick (a known
             bytes/sec rate) with ``NICE_TPU_MEMWATCH_DISK_CAPACITY``
             pinned so the forecaster's headroom is deterministic. Both
             detectors must reach **page**, with the transition visible in
             the ``nice_anomaly_state`` gauge, the ``anomaly_transition``
             flight events, and the SSE stream ("resource" + "anomaly"
             kinds). The forecaster's fitted slope and time-to-exhaustion
             are cross-checked against the injected rate.
  recover    the leaked file is deleted and the capacity override lifted:
             both detectors must return to **ok** on live evaluation.

Throughout, ``pyprof.take_sample()`` runs once per tick (PYPROF_HZ=0, so
no sampler thread races the assertions) and >= 90% of sampled stacks must
attribute to named threadspec roots. The report lands in
``MEMWATCH_r01.json``; its ``pyprof.root_shares`` block is the baseline
scripts/perf_gate.py diffs fresh profiles against.

Usage:
    python scripts/memprof_smoke.py --out MEMWATCH_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

# Knobs for the short run — set BEFORE nice_tpu imports. Manual ticks only
# (the writer periodic is parked at 1h), shrunken history buckets, memwatch
# sampling on every 0.25 s tick, a short anomaly window so the recovery
# phase slides the leak out of view in seconds, and leak-trend thresholds
# far above RSS jitter but far below the injected rate.
SMOKE_ENV = {
    "NICE_TPU_HISTORY_SECS": "3600",
    "NICE_TPU_HISTORY_1M_SECS": "2",
    "NICE_TPU_HISTORY_15M_SECS": "10",
    "NICE_TPU_MEMWATCH_SECS": "0.2",
    "NICE_TPU_PYPROF_HZ": "0",
    "NICE_TPU_MEMWATCH_HORIZON_SECS": "600",
    "NICE_TPU_ANOMALY_WINDOW_SECS": "8",
    "NICE_TPU_ANOMALY_WINDOW_SCALE": "1",
    "NICE_TPU_ANOMALY_MEM_LEAK_TREND_WARN": str(4 * 1024 * 1024),
    "NICE_TPU_ANOMALY_MEM_LEAK_TREND_PAGE": str(8 * 1024 * 1024),
}
for _k, _v in SMOKE_ENV.items():
    os.environ[_k] = _v

TICK_SECS = 0.25
BASELINE_TICKS = 16
LEAK_TICKS = 40
RECOVER_TICKS = 16
LEAK_CHUNK = 4 * 1024 * 1024          # ~16 MiB/s at the tick cadence
DISK_CAPACITY_HEADROOM = 2 << 30      # capacity = usage at leak start + 2 GiB
FORECAST_REL_TOL = 0.35               # slope/tte vs injected rate
MIN_ATTRIBUTED_FRAC = 0.90


def _get_json(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def _detector_states(ctx) -> dict:
    return {d["detector"]: d["state"] for d in ctx.anomaly.last()
            if d["detector"] in ("mem_leak_trend", "resource_exhaustion")}


def _drain_stream(sub, sse: dict) -> None:
    for evt in sub.pop_all():
        sse["kinds"][evt.kind] = sse["kinds"].get(evt.kind, 0) + 1
        if evt.kind == "anomaly":
            sse["anomaly_events"].append(
                {"name": evt.data.get("name"), "from": evt.data.get("from"),
                 "to": evt.data.get("to")}
            )


def _drive(ctx, base_url, sub, sse, ticks: int, leak_path=None) -> None:
    """Tick the observatory `ticks` times: optional leak append, one
    /status fetch (real traffic keeps the worker pool alive), one history
    tick (memwatch samples inside it), one profiler sweep."""
    from nice_tpu.obs import pyprof

    for _ in range(ticks):
        if leak_path is not None:
            with open(leak_path, "ab") as f:
                f.write(b"\0" * LEAK_CHUNK)
        _get_json(f"{base_url}/status")
        ctx.history_tick()
        pyprof.take_sample()
        _drain_stream(sub, sse)
        time.sleep(TICK_SECS)


def _check_forecast(report, problems, ctx, leak_points) -> None:
    """Cross-check the forecaster against the injected rate: fit OUR OWN
    append log with the same least-squares the detector uses, then require
    the forecaster's slope and time-to-exhaustion to agree."""
    from nice_tpu.obs import anomaly, memwatch

    since = time.time() - anomaly.window_secs()
    windowed = [(t, v) for t, v in leak_points if t >= since]
    injected = memwatch.slope_per_sec(windowed)
    fc = memwatch.forecast(ctx.history, since)
    block = report["phases"]["leak"]["forecast"] = {
        "injected_slope_bytes_per_sec": injected,
        "forecast": fc,
    }
    disk = fc.get("disk")
    if not disk or not injected:
        problems.append("forecaster produced no disk entry during the leak")
        return
    slope = disk["slope_bytes_per_sec"]
    slope_err = abs(slope - injected) / injected
    expected_tte = disk["headroom_bytes"] / injected
    tte = disk.get("tte_secs")
    tte_err = abs(tte - expected_tte) / expected_tte if tte else None
    block["checks"] = {
        "slope_rel_err": round(slope_err, 4),
        "expected_tte_secs": round(expected_tte, 2),
        "tte_secs": tte,
        "tte_rel_err": round(tte_err, 4) if tte_err is not None else None,
        "ratio": disk["ratio"],
    }
    if slope_err > FORECAST_REL_TOL:
        problems.append(
            f"forecast slope {slope / 1e6:.1f}MB/s vs injected "
            f"{injected / 1e6:.1f}MB/s ({slope_err:.0%} off, "
            f"> {FORECAST_REL_TOL:.0%})"
        )
    if tte_err is None or tte_err > FORECAST_REL_TOL:
        problems.append(
            f"forecast tte {tte} vs expected {expected_tte:.0f}s "
            f"(> {FORECAST_REL_TOL:.0%} off the injected rate)"
        )
    if disk["ratio"] < 1.0:
        problems.append(
            f"leak-phase exhaustion ratio {disk['ratio']:.2f} < 1.0 — the "
            "forecast never predicted exhaustion inside the horizon"
        )


def _check_pyprof(report, problems) -> None:
    from nice_tpu.obs import pyprof

    snap = pyprof.snapshot(top_k=5)
    total = snap["samples"]
    if not total:
        problems.append("pyprof collected no samples")
        report["pyprof"] = {"samples": 0}
        return
    shares = {root: entry["samples"] / total
              for root, entry in snap["roots"].items()}
    unattributed = shares.get(pyprof.UNATTRIBUTED, 0.0)
    attributed = 1.0 - unattributed
    report["pyprof"] = {
        "samples": total,
        "root_shares": {r: round(s, 4) for r, s in sorted(shares.items())},
        "attributed_frac": round(attributed, 4),
        "top_stacks": pyprof.top_stacks(5),
    }
    if attributed < MIN_ATTRIBUTED_FRAC:
        problems.append(
            f"only {attributed:.0%} of {total} pyprof samples attributed "
            f"to named threadspec roots (need >= "
            f"{MIN_ATTRIBUTED_FRAC:.0%})"
        )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="MEMWATCH_r01.json")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on any problem (default: warn only)")
    args = p.parse_args(argv)

    from nice_tpu import obs
    from nice_tpu.obs.series import ANOMALY_STATE, MEM_SAMPLES
    from nice_tpu.server import app as server_app
    from nice_tpu.server.db import Db

    report: dict = {
        "run": "memprof-smoke",
        "generated_ts": time.time(),
        "smoke_env": SMOKE_ENV,
        "phases": {},
        "problems": [],
    }
    problems: list = []
    sse = {"kinds": {}, "anomaly_events": []}

    with tempfile.TemporaryDirectory(prefix="memprof-smoke-") as workdir:
        db_path = os.path.join(workdir, "smoke.db")
        db = Db(db_path)
        db.seed_base(30, field_size=5_000_000)
        db.close()
        leak_dir = os.path.join(workdir, "spool")
        os.makedirs(leak_dir)
        leak_path = os.path.join(leak_dir, "leak.bin")

        srv = server_app.serve(db_path, host="127.0.0.1", port=0)
        threading.Thread(
            target=srv.serve_forever, name="memprof-smoke-httpd", daemon=True
        ).start()
        ctx = srv.context
        obs.memwatch.watch_path("spool", leak_dir)
        base_url = f"http://127.0.0.1:{srv.server_address[1]}"
        sub = ctx.stream.subscribe()
        try:
            # -- baseline: everything ok ---------------------------------
            print("== baseline: memwatch sampling, detectors ok ==")
            _drive(ctx, base_url, sub, sse, BASELINE_TICKS)
            states = _detector_states(ctx)
            report["phases"]["baseline"] = {
                "ticks": BASELINE_TICKS,
                "states": states,
                "mem_samples": int(MEM_SAMPLES.value()),
            }
            for det, state in states.items():
                if state != "ok":
                    problems.append(f"baseline: {det} is {state}, not ok")
            if MEM_SAMPLES.value() < BASELINE_TICKS / 2:
                problems.append(
                    f"baseline took only {int(MEM_SAMPLES.value())} "
                    f"memwatch samples across {BASELINE_TICKS} ticks"
                )

            # -- leak: page within the window ----------------------------
            print("== leak: injecting %.0f MB/s into the watched spool ==" %
                  (LEAK_CHUNK / TICK_SECS / 1e6))
            usage = sum(
                (obs.memwatch.summary().get("disk_bytes") or {}).values()
            )
            os.environ["NICE_TPU_MEMWATCH_DISK_CAPACITY"] = str(
                int(usage) + DISK_CAPACITY_HEADROOM
            )
            leak_points: list = []
            from nice_tpu.obs import pyprof  # noqa: F401 (driven in _drive)

            for _ in range(LEAK_TICKS):
                _drive(ctx, base_url, sub, sse, 1, leak_path=leak_path)
                leak_points.append(
                    (time.time(), os.path.getsize(leak_path))
                )
            states = _detector_states(ctx)
            report["phases"]["leak"] = {
                "ticks": LEAK_TICKS,
                "leak_chunk_bytes": LEAK_CHUNK,
                "disk_capacity_bytes": int(
                    os.environ["NICE_TPU_MEMWATCH_DISK_CAPACITY"]
                ),
                "states": states,
                "gauge_levels": {
                    det: ANOMALY_STATE.value((det,))
                    for det in ("mem_leak_trend", "resource_exhaustion")
                },
            }
            for det, state in states.items():
                if state != "page":
                    problems.append(f"leak: {det} is {state}, not page")
            _check_forecast(report, problems, ctx, leak_points)

            # -- recover: back to ok -------------------------------------
            print("== recover: leak deleted, capacity override lifted ==")
            os.remove(leak_path)
            os.environ.pop("NICE_TPU_MEMWATCH_DISK_CAPACITY", None)
            _drive(ctx, base_url, sub, sse, RECOVER_TICKS)
            states = _detector_states(ctx)
            report["phases"]["recover"] = {
                "ticks": RECOVER_TICKS,
                "states": states,
                "gauge_levels": {
                    det: ANOMALY_STATE.value((det,))
                    for det in ("mem_leak_trend", "resource_exhaustion")
                },
            }
            for det, state in states.items():
                if state != "ok":
                    problems.append(f"recover: {det} is {state}, not ok")

            # -- evidence: flight, SSE, /status, telemetry surface -------
            flights = [
                e for e in obs.flight.snapshot()
                if e.get("kind") == "anomaly_transition"
                and e.get("detector") in ("mem_leak_trend",
                                          "resource_exhaustion")
            ]
            report["transitions"] = {
                "flight_events": flights,
                "sse_kinds": sse["kinds"],
                "sse_anomaly_events": sse["anomaly_events"],
            }
            paged = {e["detector"] for e in flights
                     if e.get("to_state") == "page"}
            recovered = {e["detector"] for e in flights
                         if e.get("to_state") == "ok"}
            for det in ("mem_leak_trend", "resource_exhaustion"):
                if det not in paged:
                    problems.append(f"no flight event for {det} -> page")
                if det not in recovered:
                    problems.append(f"no flight event for {det} -> ok")
            if sse["kinds"].get("resource", 0) < 10:
                problems.append(
                    f"only {sse['kinds'].get('resource', 0)} SSE resource "
                    "events reached the subscriber"
                )
            sse_paged = {e["name"] for e in sse["anomaly_events"]
                         if e.get("to") == "page"}
            if "resource_exhaustion" not in sse_paged:
                problems.append(
                    "SSE anomaly stream never carried the "
                    "resource_exhaustion page transition"
                )

            status = _get_json(f"{base_url}/status")
            report["status_resources"] = status.get("resources")
            if not (status.get("resources") or {}).get("rss_bytes"):
                problems.append("/status resources block has no rss_bytes")
            if "spool" not in (
                (status.get("resources") or {}).get("disk_bytes") or {}
            ):
                problems.append(
                    "/status resources never picked up the watched spool"
                )
            prof = _get_json(f"{base_url}/debug/profile?fmt=json")
            report["debug_profile_roots"] = sorted(prof.get("roots", {}))

            _check_pyprof(report, problems)
        finally:
            ctx.stream.unsubscribe(sub)
            srv.shutdown()

    report["problems"] = problems
    report["ok"] = not problems
    Path(args.out).write_text(json.dumps(report, indent=1, sort_keys=True))
    print(f"wrote {args.out}")
    for prob in problems:
        print(f"FAIL: {prob}")
    if problems:
        return 1 if args.strict else 0
    print("memprof smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
