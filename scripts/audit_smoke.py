"""Audit-journal smoke check: gap-free timelines across a SIGKILL, plus a
forced stuck-field anomaly round trip.

Runs a real server subprocess with a 1 s history cadence and drives the
field lifecycle through the public API:

  1. claim + submit one detailed field to canon, then SIGKILL the server
     and restart it on the same ledger;
  2. after the restart, claim a field and deliberately sit on it with
     NICE_TPU_ANOMALY_STUCK_CLAIMS=1 — the stuck_fields detector must go
     ok -> page in /status (and nice_anomaly_state in /metrics must read
     2) while the claim is open;
  3. submit the stuck field to canon — the detector must recover to ok,
     and both transitions must be visible as anomaly_transition flight
     events at /debug/flight;
  4. every canon-promoted field's GET /fields/<id>/timeline must be
     gap-free (contiguous per-field seq from 1) and causally ordered
     (claimed before submit_accepted before canon_promoted) ACROSS the
     kill — lifecycle events commit in the same transaction as the state
     change they describe, so -9 can't shear the history.

Artifacts: timelines.json (every field's reconstructed timeline) and
anomalies.json (the observed /status anomaly snapshots + flight
transitions) in the workdir. Prints ONE JSON line. Usage:

    python scripts/audit_smoke.py [workdir]
"""

import hashlib
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE = 10  # [47, 100) -> 3 fields at field_size=20
FIELD_SIZE = 20
POLL_SECS = 0.1
ANOMALY_WAIT_SECS = 30.0


def _pick_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _start_server(db_path: str, port: int, log_path: str, env: dict):
    logf = open(log_path, "ab")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "nice_tpu.server",
            "--db", db_path, "--host", "127.0.0.1", "--port", str(port),
        ],
        stdout=logf, stderr=subprocess.STDOUT, env=env,
    )
    return proc, logf


def _wait_listening(port: int, proc, timeout: float = 30) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return False
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return True
        except OSError:
            time.sleep(POLL_SECS)
    return False


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _get_text(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode("utf-8", "replace")


def _claim(api_base: str):
    from nice_tpu.client import api_client
    from nice_tpu.core.types import SearchMode

    return api_client.get_field_from_server(
        SearchMode.DETAILED, api_base, "audit-smoke", max_retries=2
    )


def _submit(api_base: str, data) -> dict:
    """Scalar-oracle submission (no jax): same payload shape + submit_id
    derivation as client/main.py compile_results."""
    from nice_tpu.client import api_client
    from nice_tpu.core.types import DataToServer, FieldSize
    from nice_tpu.ops import scalar

    results = scalar.process_range_detailed(
        FieldSize(data.range_start, data.range_end), data.base
    )
    payload = DataToServer(
        claim_id=data.claim_id,
        username="audit-smoke",
        client_version="audit-smoke",
        unique_distribution=list(results.distribution),
        nice_numbers=list(results.nice_numbers),
    )
    content = json.dumps(payload.to_json(), sort_keys=True).encode()
    payload.submit_id = (
        f"{data.claim_id}-{hashlib.sha256(content).hexdigest()[:16]}"
    )
    return api_client.submit_field_to_server(api_base, payload, max_retries=2)


def _stuck_state(api_base: str):
    status = _get(f"{api_base}/status")
    for a in status.get("anomalies") or []:
        if a.get("detector") == "stuck_fields":
            return a.get("state")
    return None


def _wait_stuck_state(api_base: str, want: str, seen: list):
    deadline = time.monotonic() + ANOMALY_WAIT_SECS
    while time.monotonic() < deadline:
        state = _stuck_state(api_base)
        if state is not None and (not seen or seen[-1] != state):
            seen.append(state)
        if state == want:
            return True
        time.sleep(0.25)
    return False


def main() -> int:
    t_start = time.monotonic()
    if len(sys.argv) > 1:
        workdir = sys.argv[1]
        os.makedirs(workdir, exist_ok=True)
        cleanup = False
    else:
        workdir = tempfile.mkdtemp(prefix="audit-smoke-")
        cleanup = True
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from nice_tpu.server.db import Db

    db_path = os.path.join(workdir, "audit.db")
    db = Db(db_path)
    db.seed_base(BASE, field_size=FIELD_SIZE)
    field_ids = [f.field_id for f in db.get_fields_in_base(BASE)]
    db.close()

    # 1 s history cadence so the anomaly engine evaluates fast; one open
    # claim is enough to page stuck_fields.
    env = dict(
        os.environ,
        NICE_TPU_HISTORY_SECS="1",
        NICE_TPU_ANOMALY_STUCK_CLAIMS="1",
        NICE_TPU_ANOMALY_WINDOW_SECS="600",
    )
    port = _pick_port()
    api_base = f"http://127.0.0.1:{port}"
    server_log = os.path.join(workdir, "server.log")
    server, logf = _start_server(db_path, port, server_log, env)

    failures = []
    stuck_states: list = []
    line = {"workdir": workdir, "fields": len(field_ids)}
    try:
        if not _wait_listening(port, server):
            failures.append("server never listened")
            raise RuntimeError
        # Baseline: the detector must settle at ok before we force it.
        if not _wait_stuck_state(api_base, "ok", stuck_states):
            failures.append(
                f"stuck_fields never reached ok pre-kill (saw {stuck_states})"
            )

        # Phase 1: one field to canon, then a real -9 mid-run.
        first = _claim(api_base)
        _submit(api_base, first)
        server.send_signal(signal.SIGKILL)
        server.wait(timeout=30)
        logf.close()
        server, logf = _start_server(db_path, port, server_log, env)
        if not _wait_listening(port, server):
            failures.append("server never listened after restart")
            raise RuntimeError
        line["killed_and_restarted"] = True

        # Phase 2: force the stuck-field anomaly — claim and sit.
        stuck = _claim(api_base)
        if not _wait_stuck_state(api_base, "page", stuck_states):
            failures.append(
                f"stuck_fields never paged (states seen: {stuck_states})"
            )
        metrics = _get_text(f"{api_base}/metrics")
        if 'nice_anomaly_state{detector="stuck_fields"} 2' not in metrics:
            failures.append("nice_anomaly_state gauge did not read 2 (page)")

        # Phase 3: promote the stuck field — the detector must recover.
        _submit(api_base, stuck)
        if not _wait_stuck_state(api_base, "ok", stuck_states):
            failures.append(
                f"stuck_fields never recovered (states seen: {stuck_states})"
            )
        metrics = _get_text(f"{api_base}/metrics")
        if 'nice_anomaly_state{detector="stuck_fields"} 0' not in metrics:
            failures.append("nice_anomaly_state gauge did not recover to 0")
        if "page" not in stuck_states or stuck_states[-1] != "ok":
            failures.append(
                f"/status did not show ok -> page -> ok: {stuck_states}"
            )

        flight = _get(f"{api_base}/debug/flight")
        flips = [
            e for e in (flight.get("events") or [])
            if e.get("kind") == "anomaly_transition"
            and e.get("detector") == "stuck_fields"
        ]
        pairs = {(e.get("from_state"), e.get("to_state")) for e in flips}
        if ("ok", "page") not in pairs or ("page", "ok") not in pairs:
            failures.append(
                f"flight missing anomaly transitions (saw {sorted(pairs)})"
            )
        line["anomaly_states_observed"] = stuck_states
        line["anomaly_flight_transitions"] = len(flips)

        # Phase 4: every canon-promoted timeline must be gap-free and
        # causally ordered ACROSS the kill.
        timelines = {}
        canon_fields = []
        for fid in field_ids:
            tl = _get(f"{api_base}/fields/{fid}/timeline")
            events = tl["events"]
            timelines[fid] = events
            seqs = [e["seq"] for e in events]
            kinds = [e["kind"] for e in events]
            if seqs != list(range(1, len(seqs) + 1)):
                failures.append(f"field {fid}: seq gaps {seqs}")
            if not kinds or kinds[0] != "generated":
                failures.append(f"field {fid}: missing generated event")
            if "canon_promoted" not in kinds:
                continue
            canon_fields.append(fid)
            claim_idxs = [
                kinds.index(k) for k in ("claimed", "block_claimed")
                if k in kinds
            ]
            if not claim_idxs:
                failures.append(f"field {fid}: canon without a claim event")
                continue
            if not (min(claim_idxs) < kinds.index("submit_accepted")
                    < kinds.index("canon_promoted")):
                failures.append(
                    f"field {fid}: causal order violated: {kinds}"
                )
        if len(canon_fields) < 2:
            failures.append(
                f"expected >=2 canon fields (pre-kill + post-restart), "
                f"got {canon_fields}"
            )
        line["canon_fields"] = canon_fields

        # Artifacts for the CI upload.
        with open(os.path.join(workdir, "timelines.json"), "w") as f:
            json.dump({"base": BASE, "timelines": timelines}, f, indent=2)
        with open(os.path.join(workdir, "anomalies.json"), "w") as f:
            json.dump(
                {
                    "states_observed": stuck_states,
                    "final_status_anomalies": _get(
                        f"{api_base}/status"
                    ).get("anomalies"),
                    "flight_transitions": flips,
                },
                f, indent=2,
            )
    except RuntimeError:
        pass
    finally:
        if server.poll() is None:
            server.terminate()
            try:
                server.wait(timeout=15)
            except subprocess.TimeoutExpired:
                server.kill()
                server.wait(timeout=15)
        logf.close()

    line["ok"] = not failures
    line["failures"] = failures
    line["elapsed_secs"] = round(time.monotonic() - t_start, 1)
    print(json.dumps(line), flush=True)
    if cleanup and not failures:
        shutil.rmtree(workdir, ignore_errors=True)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
