#!/usr/bin/env python3
"""jaxlint CLI — jaxpr-level kernel verification for nice_tpu.

Traces every registered KernelSpec with ``jax.make_jaxpr`` on abstract
inputs (CPU-only; no accelerator needed) and runs the J-rule family over
the traced plans: dtype-flow, carry-headroom interval proofs, donation
discipline, transfer purity, recompile-surface audit, and KernelSpec
contract drift. Shares nicelint's ratchet baseline and escape grammar.

Usage:
    python scripts/jaxlint.py                  # report vs ratchet baseline
    python scripts/jaxlint.py --strict         # CI gate: also fail stale
                                               # entries and skipped traces
    python scripts/jaxlint.py --update-baseline
    python scripts/jaxlint.py --json out.json  # archive the full report
    python scripts/jaxlint.py --rules J2,J3    # run a subset
    python scripts/jaxlint.py --bases 40       # quick local sweep

Exit codes: 0 clean, 1 new violations (or stale entries / skipped traces
under --strict), 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# Tracing is abstract; never let jaxlint grab a real accelerator.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from nice_tpu.analysis import core  # noqa: E402
from nice_tpu.analysis import jaxrules  # noqa: E402
from nice_tpu.analysis.jaxrules import tracer  # noqa: E402
from nice_tpu.utils import knobs  # noqa: E402

FAMILY = ("J1", "J2", "J3", "J4", "J5", "J6",
          core.DEAD_SUPPRESSION_RULE)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=REPO_ROOT)
    ap.add_argument("--strict", action="store_true",
                    help="fail on stale baseline entries and skipped traces")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite this family's slice of the shared baseline")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full report (violations + trace/proof "
                         "stats) as JSON")
    ap.add_argument("--rules", metavar="IDS",
                    default=knobs.JAXLINT_RULES.get(),
                    help="comma-separated J-rule subset (e.g. J2,J3)")
    ap.add_argument("--bases", metavar="LIST",
                    default=knobs.JAXLINT_BASES.get(),
                    help="comma-separated base sweep to trace at")
    ap.add_argument("--budget", type=float, metavar="SECS",
                    default=knobs.JAXLINT_TRACE_BUDGET_SECS.get(),
                    help="wall-clock budget for the trace sweep")
    args = ap.parse_args(argv)

    try:
        bases = sorted({int(b) for b in str(args.bases).split(",") if
                        b.strip()})
    except ValueError:
        print(f"jaxlint: bad --bases {args.bases!r}", file=sys.stderr)
        return 2
    if not bases:
        print("jaxlint: empty base sweep", file=sys.stderr)
        return 2

    root = os.path.abspath(args.root)
    project = core.Project(root)

    t0 = time.monotonic()
    ctx = tracer.build_context(root, bases, budget_secs=args.budget)
    trace_secs = time.monotonic() - t0
    print(f"jaxlint: traced {len(ctx.traces)} plans over bases "
          f"{bases} in {trace_secs:.1f}s"
          + (f" ({len(ctx.skipped)} skipped)" if ctx.skipped else ""))

    only = [r.strip().upper() for r in args.rules.split(",")] \
        if args.rules else None
    ctx.report["j5_max_variants"] = knobs.JAXLINT_MAX_VARIANTS.get()
    violations, used = jaxrules.run_jax_rules(project, ctx, only=only)

    if only is None:
        # the dead-suppression audit (S1) needs every J-rule's usage data,
        # so it only runs on full (non --rules) invocations
        jrule_ids = {r for r in FAMILY if r != core.DEAD_SUPPRESSION_RULE}
        dead, _ = core.filter_allowed(
            project, core.dead_suppressions(project, jrule_ids, used))
        violations = sorted(
            violations + dead,
            key=lambda v: (v.path, v.line, v.rule, v.detail))

    baseline = core.filter_baseline(core.load_baseline(root), FAMILY)
    if only:
        baseline = core.filter_baseline(baseline, set(only))
    new, stale = core.diff_against_baseline(violations, baseline)

    if args.update_baseline:
        old = core.load_baseline(root)
        # preserve the other family's keys — the baseline file is shared
        entries = {k: v for k, v in old.items()
                   if k not in core.filter_baseline(old, FAMILY)}
        for v in violations:
            entries[v.key] = old.get(v.key, "TODO: justify or fix")
        core.save_baseline(root, entries)
        print(f"jaxlint: baseline rewritten ({len(new)} new, "
              f"{len(stale)} removed; other families preserved)")
        return 0

    if args.json:
        report = {
            "bases": bases,
            "trace_secs": round(trace_secs, 2),
            "violations": [v.to_json() for v in violations],
            "new": [v.to_json() for v in new],
            "stale_baseline_keys": stale,
            "baselined": len(violations) - len(new),
            "skipped_traces": ctx.skipped,
            "context": ctx.report,
        }
        with open(args.json, "w", encoding="utf-8") as f:  # nicelint: allow A1 (CI artifact, not state)
            json.dump(report, f, indent=1, default=str)
            f.write("\n")

    for v in new:
        print(f"{v.path}:{v.line}: {v.rule}: {v.message}")
    if stale:
        print(f"jaxlint: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (fixed violations "
              "still listed — run --update-baseline to burn them down):")
        for key in stale:
            print(f"  stale: {key}")
    if ctx.skipped:
        print(f"jaxlint: {len(ctx.skipped)} trace(s) skipped "
              f"(budget {args.budget:.0f}s):")
        for entry in ctx.skipped:
            print(f"  skipped: {entry}")

    baselined = len(violations) - len(new)
    print(f"jaxlint: {len(new)} new, {baselined} baselined, "
          f"{len(stale)} stale")
    if new:
        return 1
    if args.strict and (stale or ctx.skipped):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
