#!/usr/bin/env python
"""Inspect a number's square-cube pandigital properties per base (reference
scripts/inspect_number.py: valid-candidate window discovery + per-base digit
breakdown).

For each base where n falls in the valid range (digits(n^2) + digits(n^3)
== b — necessary for niceness), prints n^2 and n^3 in base b, the combined
digit multiset, num_uniques, niceness, the position inside the search range,
and a digit histogram.

Usage:
    python scripts/inspect_number.py 69
    python scripts/inspect_number.py 69 --base 10
    python scripts/inspect_number.py 3141592653589793 --min-base 40 --max-base 60
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu.core import base_range  # noqa: E402
from nice_tpu.ops import scalar  # noqa: E402

DIGITS36 = "0123456789abcdefghijklmnopqrstuvwxyz"


def to_base(v: int, base: int) -> list[int]:
    """Base-b digit list, most significant first."""
    if v == 0:
        return [0]
    out = []
    while v:
        v, d = divmod(v, base)
        out.append(d)
    return out[::-1]


def fmt_digits(digits: list[int], base: int) -> str:
    if base <= 36:
        return "".join(DIGITS36[d] for d in digits)
    return "[" + " ".join(str(d) for d in digits) + "]"


def inspect_in_base(n: int, base: int) -> None:
    sq, cu = n * n, n * n * n
    d_sq, d_cu = to_base(sq, base), to_base(cu, base)
    combined = d_sq + d_cu
    uniques = scalar.get_num_unique_digits(n, base)
    r = base_range.get_base_range(base)
    print(f"base {base}:")
    print(f"  n^2 = {sq} = {fmt_digits(d_sq, base)} ({len(d_sq)} digits)")
    print(f"  n^3 = {cu} = {fmt_digits(d_cu, base)} ({len(d_cu)} digits)")
    print(
        f"  combined digits: {len(combined)} of {base}; "
        f"num_uniques = {uniques}; niceness = {uniques / base:.4f}"
        + ("  <- NICE!" if uniques == base else "")
    )
    if r is not None:
        pos = (n - r[0]) / max(1, r[1] - r[0])
        print(
            f"  search range: [{r[0]}, {r[1]}) — position {100 * pos:.2f}% through"
        )
    hist = [0] * base
    for d in combined:
        hist[d] += 1
    missing = [d for d in range(base) if hist[d] == 0]
    dupes = {d: c for d, c in enumerate(hist) if c > 1}
    if missing:
        print(f"  missing digits: {missing}")
    if dupes:
        print(f"  duplicated digits (digit: count): {dupes}")
    print()


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("number", type=int)
    p.add_argument("--base", type=int, help="inspect only this base")
    p.add_argument("--min-base", type=int, default=4)
    p.add_argument("--max-base", type=int, default=120)
    args = p.parse_args()
    n = args.number
    if n < 2:
        print("number must be >= 2", file=sys.stderr)
        return 1

    if args.base is not None:
        inspect_in_base(n, args.base)
        return 0

    found = []
    for base in range(args.min_base, args.max_base + 1):
        sq_digits = len(to_base(n * n, base))
        cu_digits = len(to_base(n * n * n, base))
        if sq_digits + cu_digits == base:
            found.append(base)
    if not found:
        print(
            f"{n} is not a valid candidate in any base in "
            f"[{args.min_base}, {args.max_base}] (digit counts never sum to b)"
        )
        return 0
    print(f"{n} is a valid candidate in base(s) {found}\n")
    for base in found:
        inspect_in_base(n, base)
    return 0


if __name__ == "__main__":
    sys.exit(main())
