#!/usr/bin/env python
"""CI telemetry + fleet smoke: run a tiny instrumented field search and verify
the pipeline metrics and trace spans come out the other end, then run a live
server with two clients and verify the fleet observability surfaces.

Part 1 (single-process engine telemetry): a small detailed field on the jax
backend with NICE_TPU_TRACE pointed at a temp file; greps the rendered
/metrics text for the engine series names and the trace file for span events.

Part 2 (fleet): an in-process API server + two simulated clients, each doing
a real claim -> scan -> submit cycle inside its claim-derived trace context.
Verifies the distributed-tracing acceptance path (client AND server spans for
one field share a single trace_id), that /status's fleet block reports both
clients, and that a SIGUSR2 flight-recorder dump is valid JSON.

Exits nonzero (with a diff of what's missing) if any expected signal is
absent — catching the failure mode where a refactor silently disconnects the
instrumentation while the tests that merely import obs still pass.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

EXPECTED_SERIES = [
    "nice_engine_batch_kernel_seconds_bucket",
    'nice_engine_batch_kernel_seconds_count{path="detailed"}',
    "nice_engine_dispatch_window_occupancy",
    "nice_engine_stride_window_occupancy",
    "nice_engine_host_fallback_total",
    "nice_engine_audit_total",
    'nice_engine_numbers_total{mode="detailed"}',
    "nice_mesh_devices",
    "nice_backend_init_seconds",
    "nice_client_request_seconds",
    "nice_trace_span_seconds",
    "nice_fleet_clients",
    "nice_flight_events_total",
]

EXPECTED_SPANS = ["engine.detailed"]


def _engine_smoke(trace_path: str, failures: list) -> None:
    from nice_tpu import obs
    from nice_tpu.core.types import FieldSize
    from nice_tpu.obs.series import ENGINE_NUMBERS
    from nice_tpu.ops import engine, scalar

    rng = FieldSize(47, 100)  # base 10's full valid range: tiny but real
    want = scalar.process_range_detailed(rng, 10)
    got = engine.process_range_detailed(rng, 10, backend="jax", batch_size=256)
    if got != want:
        failures.append("engine: instrumented jax run diverged from scalar")
        return

    text = obs.render()
    for name in EXPECTED_SERIES:
        if name not in text:
            failures.append(f"metrics: missing series {name!r}")
    if ENGINE_NUMBERS.labels("detailed").value() < rng.range_size:
        failures.append("metrics: nice_engine_numbers_total did not count the run")

    try:
        with open(trace_path) as f:
            events = [json.loads(line) for line in f if line.strip()]
    except FileNotFoundError:
        events = []
        failures.append(f"trace: no sink written at {trace_path}")
    names = {e.get("name") for e in events}
    for span in EXPECTED_SPANS:
        if span not in names:
            failures.append(
                f"trace: no span events for {span!r} (saw {sorted(names)})"
            )
    for e in events:
        if e.get("event") == "end" and "wall_secs" not in e:
            failures.append(f"trace: end event without wall_secs: {e}")


def _run_client(base_url: str, username: str) -> int:
    """One simulated fleet client: claim -> scan -> submit with telemetry
    piggybacked and a heartbeat, all inside the claim's trace context.
    Returns the claim id."""
    from nice_tpu import obs
    from nice_tpu.client import api_client
    from nice_tpu.client.main import compile_results, process_field
    from nice_tpu.core.types import SearchMode
    from nice_tpu.obs import telemetry

    data = api_client.get_field_from_server(
        SearchMode.DETAILED, base_url, username, max_retries=0
    )
    with obs.trace_context(obs.claim_trace_id(data.claim_id)):
        obs.trace_event("client.claim", claim=data.claim_id, base=data.base)
        results, _ = process_field(data, SearchMode.DETAILED, "scalar", 1024)
        submission = compile_results(
            data, results, SearchMode.DETAILED, username
        )
        submission.telemetry = telemetry.snapshot(
            username=username, backend="scalar"
        )
        api_client.submit_field_to_server(base_url, submission, max_retries=0)
    api_client.post_telemetry(
        base_url, telemetry.snapshot(username=username, backend="scalar")
    )
    return data.claim_id


def _fleet_smoke(trace_path: str, flight_dir: str, failures: list) -> None:
    from nice_tpu import obs
    from nice_tpu.obs import telemetry
    from nice_tpu.server import app as server_app
    from nice_tpu.server.db import Db

    db_path = os.path.join(tempfile.mkdtemp(prefix="nice-fleet-"), "smoke.db")
    db = Db(db_path)
    db.seed_base(10, field_size=20)  # [47,100) -> 3 fields
    db.close()
    srv = server_app.serve(db_path, host="127.0.0.1", port=0, prefill=True)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base_url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        claim_ids = [
            _run_client(base_url, "smoke-a"),
            _run_client(base_url, "smoke-b"),
        ]

        # Acceptance: one field's spans on BOTH sides share a single
        # trace_id covering claim -> scan -> submit.
        with open(trace_path) as f:
            events = [json.loads(line) for line in f if line.strip()]
        tid = obs.claim_trace_id(claim_ids[0])
        by_side = {"client.submit": 0, "server.submit": 0,
                   "client.claim": 0, "engine.scalar": 0}
        for e in events:
            if e.get("trace_id") == tid and e.get("name") in by_side:
                by_side[e["name"]] += 1
        for name, n in by_side.items():
            if not n:
                failures.append(
                    f"fleet trace: no {name!r} events with trace_id {tid}"
                )

        # /status fleet block reports both clients.
        with urllib.request.urlopen(f"{base_url}/status", timeout=10) as r:
            fleet = json.loads(r.read())["fleet"]
        ids = {c["client_id"] for c in fleet["clients"]}
        for user in ("smoke-a", "smoke-b"):
            if telemetry.client_id(user) not in ids:
                failures.append(
                    f"fleet block: client {user!r} missing (saw {sorted(ids)})"
                )
        if fleet["submissions_total"] < 2:
            failures.append(
                f"fleet block: expected >=2 submissions, "
                f"saw {fleet['submissions_total']}"
            )

        # SIGUSR2 dumps the flight ring as valid JSON.
        if hasattr(signal, "SIGUSR2"):
            obs.flight.install()
            os.kill(os.getpid(), signal.SIGUSR2)
            dump = os.path.join(
                flight_dir, f"nice-flight-{os.getpid()}-sigusr2.json"
            )
            deadline = time.monotonic() + 5.0
            while not os.path.exists(dump) and time.monotonic() < deadline:
                time.sleep(0.05)
            if not os.path.exists(dump):
                failures.append(f"flight: no SIGUSR2 dump at {dump}")
            else:
                try:
                    payload = json.loads(open(dump).read())
                    if payload["reason"] != "sigusr2" or not payload["events"]:
                        failures.append(f"flight: malformed dump {payload}")
                except (ValueError, KeyError) as e:
                    failures.append(f"flight: SIGUSR2 dump not valid JSON: {e}")
    finally:
        srv.shutdown()


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="nice-obs-")
    trace_path = os.path.join(tmp, "trace.jsonl")
    flight_dir = os.path.join(tmp, "flight")
    os.environ["NICE_TPU_TRACE"] = trace_path
    os.environ["NICE_TPU_FLIGHT_DIR"] = flight_dir
    os.environ.setdefault("NICE_TPU_SHARD", "0")  # single-chip engine path

    failures: list = []
    _engine_smoke(trace_path, failures)
    _fleet_smoke(trace_path, flight_dir, failures)

    if failures:
        print("telemetry smoke FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1

    print(
        f"telemetry smoke OK: {len(EXPECTED_SERIES)} series present, "
        f"fleet block reported 2 clients, trace sink at {trace_path}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
