#!/usr/bin/env python
"""CI telemetry smoke: run a tiny instrumented field search and verify the
pipeline metrics and trace spans actually come out the other end.

Runs a small detailed field on the scalar and jax backends with
NICE_TPU_TRACE pointed at a temp file, then greps the rendered /metrics text
for the engine series names and the trace file for span events. Exits
nonzero (with a diff of what's missing) if any expected signal is absent —
catching the failure mode where a refactor silently disconnects the
instrumentation while the tests that merely import obs still pass.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

EXPECTED_SERIES = [
    "nice_engine_batch_kernel_seconds_bucket",
    'nice_engine_batch_kernel_seconds_count{path="detailed"}',
    "nice_engine_dispatch_window_occupancy",
    "nice_engine_stride_window_occupancy",
    "nice_engine_host_fallback_total",
    "nice_engine_audit_total",
    'nice_engine_numbers_total{mode="detailed"}',
    "nice_mesh_devices",
    "nice_backend_init_seconds",
    "nice_client_request_seconds",
    "nice_trace_span_seconds",
]

EXPECTED_SPANS = ["engine.detailed"]


def main() -> int:
    trace_path = os.path.join(tempfile.mkdtemp(prefix="nice-obs-"), "trace.jsonl")
    os.environ["NICE_TPU_TRACE"] = trace_path
    os.environ.setdefault("NICE_TPU_SHARD", "0")  # single-chip engine path

    from nice_tpu import obs
    from nice_tpu.core.types import FieldSize
    from nice_tpu.obs.series import ENGINE_NUMBERS
    from nice_tpu.ops import engine, scalar

    rng = FieldSize(47, 100)  # base 10's full valid range: tiny but real
    want = scalar.process_range_detailed(rng, 10)
    got = engine.process_range_detailed(rng, 10, backend="jax", batch_size=256)
    if got != want:
        print("FAIL: instrumented jax run diverged from scalar", file=sys.stderr)
        return 1

    failures = []

    text = obs.render()
    for name in EXPECTED_SERIES:
        if name not in text:
            failures.append(f"metrics: missing series {name!r}")
    if ENGINE_NUMBERS.labels("detailed").value() < rng.range_size:
        failures.append("metrics: nice_engine_numbers_total did not count the run")

    try:
        with open(trace_path) as f:
            events = [json.loads(line) for line in f if line.strip()]
    except FileNotFoundError:
        events = []
        failures.append(f"trace: no sink written at {trace_path}")
    names = {e.get("name") for e in events}
    for span in EXPECTED_SPANS:
        if span not in names:
            failures.append(f"trace: no span events for {span!r} (saw {sorted(names)})")
    for e in events:
        if e.get("event") == "end" and "wall_secs" not in e:
            failures.append(f"trace: end event without wall_secs: {e}")

    if failures:
        print("telemetry smoke FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1

    print(
        f"telemetry smoke OK: {len(EXPECTED_SERIES)} series present, "
        f"{len(events)} trace events in {trace_path}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
