"""Multi-tenant scheduler smoke check against a real server.

Boots the real API server IN PROCESS (app.serve on a thread — same
router/writer/journal stack the subprocess smokes exercise), seeds a
detailed base and a niceonly base, then drives a three-tenant
MultiTenantScheduler over ServerSource:

  1. tenants: a priority-3 detailed canon tenant (base 10), a priority-1
     niceonly tenant (base 12), and the standing near-miss mining tenant
     (priority 0, detailed re-scans of the base-10 inventory) — claims
     carry the tenant name + base window through the public API;
  2. after a fixed number of interleaved rounds, flip the mining tenant's
     priority to 5 mid-run (TenantRegistry.replace) and drain: mining's
     share of scheduled pages must SHIFT UP vs the pre-flip phase;
  3. the post-flip phase must run with ZERO new stepprof compile seconds
     and zero compile-cache executable misses — tenant page switches
     re-enter warm executables, never recompile;
  4. after the drain, every ledger row under every tenant
     (db.get_submissions_by_tenant) must match the scalar single-tenant
     oracle for its field byte-for-byte, and /status must carry the
     per-(tenant, mode, base) rollup with a claim+submission count for
     all three tenants.

Artifact: SCHED_r01.json in the workdir. Prints ONE JSON line. Usage:

    python scripts/sched_smoke.py [workdir]
"""

import dataclasses
import json
import os
import shutil
import socket
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DETAILED_BASE = 10  # [47, 100) -> 11 fields at field_size=5
NICEONLY_BASE = 12  # [144, 330)
FIELD_SIZE_DETAILED = 5
FIELD_SIZE_NICEONLY = 20
PRE_FLIP_ROUNDS = 8
BATCH = 512


def _pick_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _start_server(db_path: str, port: int):
    """In-process server on a daemon thread (threadspec: sched-smoke-httpd).
    Returns (server, thread); server.shutdown() stops it."""
    from nice_tpu.server import app

    server = app.serve(db_path, "127.0.0.1", port)
    thread = threading.Thread(
        target=server.serve_forever, name="sched-smoke-httpd", daemon=True
    )
    thread.start()
    return server, thread


def _wait_listening(port: int, timeout: float = 30) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return True
        except OSError:
            time.sleep(0.1)
    return False


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _compile_secs() -> float:
    """Total stepprof-attributed compile seconds across all plan keys."""
    from nice_tpu.obs import stepprof

    return sum(
        v.get("compile", 0.0) for v in stepprof.cumulative().values()
    )


def _page_shares(stats: dict) -> dict:
    pages = {t: s["pages"] for t, s in stats["tenants"].items()}
    total = sum(pages.values()) or 1
    return {t: p / total for t, p in pages.items()}


def _check_ledger(db, failures: list) -> dict:
    """Every tenant submission vs the scalar single-tenant oracle."""
    from nice_tpu.core import distribution_stats, number_stats
    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import scalar

    field_map = {}
    for base in (DETAILED_BASE, NICEONLY_BASE):
        for f in db.get_fields_in_base(base):
            field_map[f.field_id] = (base, f.range_start, f.range_end)
    checked = {}
    for tenant in ("canon", "nice", "mining"):
        subs = db.get_submissions_by_tenant(tenant)
        if not subs:
            failures.append(f"tenant {tenant}: no ledger submissions")
            continue
        ok = 0
        for sub in subs:
            base, start, end = field_map[sub.field_id]
            rng = FieldSize(start, end)
            if sub.distribution is not None:
                want = scalar.process_range_detailed(rng, base)
                got_dist = distribution_stats.shrink_distribution(
                    sub.distribution
                )
                if got_dist != list(want.distribution):
                    failures.append(
                        f"tenant {tenant} field {sub.field_id}: distribution"
                        " diverges from the scalar oracle"
                    )
                    continue
            else:
                want = scalar.process_range_niceonly(rng, base, None)
            got_nums = number_stats.shrink_numbers(sub.numbers)
            if got_nums != list(want.nice_numbers):
                failures.append(
                    f"tenant {tenant} field {sub.field_id}: numbers diverge"
                    " from the scalar oracle"
                )
                continue
            ok += 1
        checked[tenant] = {"submissions": len(subs), "oracle_matches": ok}
    return checked


def main() -> int:
    t_start = time.monotonic()
    if len(sys.argv) > 1:
        workdir = sys.argv[1]
        os.makedirs(workdir, exist_ok=True)
        cleanup = False
    else:
        workdir = tempfile.mkdtemp(prefix="sched-smoke-")
        cleanup = True
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("NICE_TPU_HOST_NICEONLY_MAX", "0")
    # Small pages (batch x 2-segment megaloop) + compile attribution on.
    os.environ.setdefault("NICE_TPU_MEGALOOP_SEGMENT", "2")
    os.environ["NICE_TPU_STEPPROF"] = "1"

    from nice_tpu.ops import compile_cache
    from nice_tpu.sched import (
        MultiTenantScheduler,
        ServerSource,
        TenantRegistry,
        TenantSpec,
        near_miss_tenant,
    )
    from nice_tpu.server.db import Db

    failures = []
    db_path = os.path.join(workdir, "sched.db")
    db = Db(db_path)
    db.seed_base(DETAILED_BASE, field_size=FIELD_SIZE_DETAILED)
    db.seed_base(NICEONLY_BASE, field_size=FIELD_SIZE_NICEONLY)
    db.close()

    port = _pick_port()
    server, server_thread = _start_server(db_path, port)
    api_base = f"http://127.0.0.1:{port}"
    line = {"ok": False, "workdir": workdir}
    try:
        if not _wait_listening(port):
            failures.append("server never listened")
            raise RuntimeError("boot")

        mining = dataclasses.replace(
            near_miss_tenant(DETAILED_BASE, name="mining"),
            backend="jnp", batch_size=BATCH,
        )
        registry = TenantRegistry([
            TenantSpec(
                name="canon", mode="detailed", base=DETAILED_BASE,
                priority=3, slo_page_secs=5.0, backend="jnp",
                batch_size=BATCH,
            ),
            TenantSpec(
                name="nice", mode="niceonly", base=NICEONLY_BASE,
                priority=1, backend="jnp", batch_size=BATCH,
            ),
            mining,
        ])
        source = ServerSource(api_base, "sched-smoke")
        sched = MultiTenantScheduler(
            registry, source, policy="deficit", page_batches=1,
            quantum_secs=1e-9,
        )

        # Phase 1: interleave under the seeded priorities (compiles land
        # here, via warm() and any first-dispatch stragglers).
        stats1 = sched.run(max_rounds=PRE_FLIP_ROUNDS)
        shares1 = _page_shares(stats1)
        compile_secs1 = _compile_secs()
        cc1 = compile_cache.counts()

        # Phase 2: flip mining 0 -> 5 mid-run and drain.
        registry.replace(dataclasses.replace(mining, priority=5))
        stats2 = sched.run()
        shares2 = _page_shares(stats2)
        compile_secs2 = _compile_secs()
        cc2 = compile_cache.counts()

        # Occupancy shifted: mining's page share rose after the flip.
        phase2_pages = {
            t: stats2["tenants"][t]["pages"] - stats1["tenants"][t]["pages"]
            for t in stats2["tenants"]
        }
        phase2_total = sum(phase2_pages.values()) or 1
        mining_share_2 = phase2_pages["mining"] / phase2_total
        if mining_share_2 <= shares1.get("mining", 0.0):
            failures.append(
                f"priority flip did not shift occupancy: mining share"
                f" {shares1.get('mining', 0.0):.3f} -> {mining_share_2:.3f}"
            )

        # Zero recompile stalls across post-flip tenant switches.
        compile_delta = compile_secs2 - compile_secs1
        miss_delta = cc2["executable_misses"] - cc1["executable_misses"]
        if compile_delta > 0 or miss_delta > 0:
            failures.append(
                f"post-flip phase recompiled: {compile_delta:.3f}s stepprof"
                f" compile, {miss_delta} executable misses"
            )

        status = _get(f"{api_base}/status")
        rollup = status.get("tenants") or []
        seen = {r["tenant"] for r in rollup}
        for want in ("canon", "nice", "mining"):
            if want not in seen:
                failures.append(f"/status tenants rollup missing {want!r}")

        line.update({
            "rounds": stats2["rounds"],
            "occupancy": round(stats2["occupancy"], 4),
            "page_shares_pre_flip": {
                t: round(v, 4) for t, v in shares1.items()
            },
            "page_shares_final": {t: round(v, 4) for t, v in shares2.items()},
            "mining_share_post_flip": round(mining_share_2, 4),
            "post_flip_compile_secs": round(compile_delta, 4),
            "post_flip_executable_misses": miss_delta,
            "status_rollup": rollup,
        })
    except RuntimeError:
        pass
    finally:
        server.shutdown()
        server_thread.join(timeout=10)

    if not failures:
        db = Db(db_path)
        try:
            line["ledger"] = _check_ledger(db, failures)
        finally:
            db.close()

    line["ok"] = not failures
    line["failures"] = failures
    line["elapsed_secs"] = round(time.monotonic() - t_start, 1)
    artifact = os.path.join(workdir, "SCHED_r01.json")
    with open(artifact, "w") as fh:
        json.dump(line, fh, indent=2, sort_keys=True)
        fh.write("\n")
    line["artifact"] = artifact
    print(json.dumps(line, sort_keys=True))
    if cleanup and not failures:
        shutil.rmtree(workdir, ignore_errors=True)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
