"""Two-run persistent-compilation-cache smoke check.

Runs a tiny two-field engine job (one detailed field, one niceonly field,
base 10) with ``JAX_COMPILATION_CACHE_DIR`` pointed at the directory given
as argv[1], and prints ONE JSON line with wall timings and the
``nice_compile_cache_events_total`` counters.

CI runs it twice with the same cache directory and asserts that the second
run reports nonzero persistent-cache hits and a faster init+warm phase —
proving the cache actually round-trips through disk, not just that the env
var is set. Usage:

    python scripts/compile_cache_smoke.py /tmp/jax-cache
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    if len(sys.argv) > 1:
        os.environ["JAX_COMPILATION_CACHE_DIR"] = sys.argv[1]
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    t0 = time.monotonic()
    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import compile_cache, engine

    init_secs = time.monotonic() - t0

    # warm_detailed runs setup() + AOT-compiles the batch kernel — with a
    # warm persistent cache the XLA compile inside .lower().compile() is a
    # disk deserialize, which is what the second CI run asserts on.
    t1 = time.monotonic()
    engine.warm_detailed(10, batch_size=128)
    warm_secs = time.monotonic() - t1

    t2 = time.monotonic()
    detailed = engine.process_range_detailed(
        FieldSize(47, 100), 10, backend="jax", batch_size=128
    )
    niceonly = engine.process_range_niceonly(
        FieldSize(47, 100), 10, backend="jnp", batch_size=128
    )
    run_secs = time.monotonic() - t2

    ok = (
        any(n.number == 69 for n in detailed.nice_numbers)
        and [n.number for n in niceonly.nice_numbers] == [69]
    )
    line = {
        "ok": ok,
        "init_secs": round(init_secs, 3),
        "warm_secs": round(warm_secs, 3),
        "run_secs": round(run_secs, 3),
        "cache_dir": os.environ.get("JAX_COMPILATION_CACHE_DIR"),
    }
    line.update(compile_cache.counts())
    print(json.dumps(line), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
