#!/usr/bin/env python
"""Locate windows where the MSD filter is unusually effective or ineffective
(reference scripts/find_msd_benchmark_ranges.rs:10-39) — the source of the
msd-effective / msd-ineffective benchmark fields.

Scans windows across a base's range, measuring surviving fraction after the
recursive filter, and prints the extremes.

Usage: python scripts/find_msd_benchmark_ranges.py --base 50 --window 10000000 --samples 64
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu.core import base_range  # noqa: E402
from nice_tpu.core.types import FieldSize  # noqa: E402
from nice_tpu.ops import msd_filter  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--base", type=int, default=50)
    p.add_argument("--window", type=int, default=10_000_000)
    p.add_argument("--samples", type=int, default=64)
    args = p.parse_args()
    lo, hi = base_range.get_base_range(args.base)
    span = hi - lo - args.window
    if span <= 0:
        print("window larger than base range", file=sys.stderr)
        return 1
    results = []
    for i in range(args.samples):
        start = lo + (span * i) // max(1, args.samples - 1)
        fs = FieldSize(start, start + args.window)
        surviving = msd_filter.get_valid_ranges(fs, args.base)
        frac = sum(r.size() for r in surviving) / args.window
        results.append((frac, start))
        print(f"start={start} surviving={frac:.4f} ranges={len(surviving)}")
    results.sort()
    print(f"\nmost effective (least surviving): start={results[0][1]} "
          f"({results[0][0]:.4f} surviving)")
    print(f"least effective (most surviving): start={results[-1][1]} "
          f"({results[-1][0]:.4f} surviving)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
