"""Critical-path smoke check: a deliberately stalled writer actor must show
up as the dominant ``writer_wait`` segment in reconciled per-field
waterfalls, while the SSE stream delivers the same run live.

Runs a real server subprocess on the async core with a 1 s history cadence
and ``NICE_TPU_FAULTS="writer.batch:<stall>"`` — every writer batch sleeps
*before* ``t_begin`` is stamped, so the injected stall lands in the
actor-measured per-op queue wait (the ``writer_wait`` segment is measured
at the source, not inferred from endpoint latency). Then:

  1. connect a Server-Sent-Events probe to GET /events/stream (it must
     say hello, then carry the run's journal events live);
  2. seed a 3-field base AFTER the server is listening (seeding first
     would book the multi-second server boot into queue_wait and swamp
     the stall we are trying to attribute);
  3. run three concurrent in-process clients through the public API:
     claim detailed -> scalar-oracle submit -> canon, then POST the
     buffered client trace events (claim/submit round-trips) via
     /telemetry so the server can merge them into the timelines;
  4. GET /critpath must report all 3 fields with reconciled waterfalls
     (|residual| <= tolerance) whose dominant segment — per field and
     fleet-wide — is writer_wait, at >= one injected stall each, and the
     writer_wait share gauge must be live in /metrics;
  5. resume the stream from a mid-run cursor (?since=<id>, the same
     durable cursor /events?since= uses) — the replay must contain every
     journal id after the cursor exactly once (no duplicates, no holes).

Artifact: critpath.json (the /critpath snapshot + stream probe stats) in
the workdir. Prints ONE JSON line. Usage:

    python scripts/critpath_smoke.py [workdir]
"""

import hashlib
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE = 10  # [47, 100) -> 3 fields at field_size=20
FIELD_SIZE = 20
CLIENTS = 3
STALL_SECS = 0.4
POLL_SECS = 0.25
MERGE_WAIT_SECS = 30.0


def _pick_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _start_server(db_path: str, port: int, log_path: str, env: dict):
    logf = open(log_path, "ab")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "nice_tpu.server",
            "--db", db_path, "--host", "127.0.0.1", "--port", str(port),
        ],
        stdout=logf, stderr=subprocess.STDOUT, env=env,
    )
    return proc, logf


def _wait_listening(port: int, proc, timeout: float = 30) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return False
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return True
        except OSError:
            time.sleep(0.1)
    return False


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _get_text(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode("utf-8", "replace")


class StreamProbe(threading.Thread):
    """Background SSE reader: parses id/event/data frames off a live
    GET /events/stream connection until stopped. The 2 s server heartbeat
    keeps the socket read from ever blocking near the urlopen timeout."""

    def __init__(self, url: str, name: str = "critpath-smoke-stream"):
        super().__init__(name=name, daemon=True)
        self.url = url
        self.frames: list = []  # (id_str_or_None, event, data_str)
        self.heartbeats = 0
        self.error = None
        self._halt = threading.Event()
        self._resp = None

    def run(self):
        try:
            self._resp = urllib.request.urlopen(self.url, timeout=30)
            cur = {"id": None, "event": "message", "data": []}
            for raw in self._resp:
                if self._halt.is_set():
                    break
                line = raw.decode("utf-8", "replace").rstrip("\r\n")
                if not line:
                    if cur["data"]:
                        self.frames.append(
                            (cur["id"], cur["event"], "\n".join(cur["data"]))
                        )
                    cur = {"id": None, "event": "message", "data": []}
                elif line.startswith(":"):
                    self.heartbeats += 1
                elif line.startswith("id:"):
                    cur["id"] = line[3:].strip()
                elif line.startswith("event:"):
                    cur["event"] = line[6:].strip()
                elif line.startswith("data:"):
                    cur["data"].append(line[5:].strip())
        except Exception as exc:  # noqa: BLE001 — reported via self.error
            if not self._halt.is_set():
                self.error = repr(exc)

    def stop(self):
        self._halt.set()
        try:
            if self._resp is not None:
                self._resp.close()
        except Exception:  # noqa: BLE001 — teardown only
            pass

    def events(self, name: str) -> list:
        return [f for f in self.frames if f[1] == name]

    def journal_ids(self) -> list:
        out = []
        for fid, _, _ in self.events("journal"):
            try:
                out.append(int(fid))
            except (TypeError, ValueError):
                pass
        return out

    def journal_kinds(self) -> set:
        kinds = set()
        for _, _, data in self.events("journal"):
            try:
                kinds.add(json.loads(data).get("kind"))
            except (ValueError, TypeError):
                pass
        return kinds


def _claim(api_base: str):
    from nice_tpu.client import api_client
    from nice_tpu.core.types import SearchMode

    return api_client.get_field_from_server(
        SearchMode.DETAILED, api_base, "critpath-smoke", max_retries=2
    )


def _submit(api_base: str, data) -> dict:
    """Scalar-oracle submission (no jax): same payload shape + submit_id
    derivation as client/main.py compile_results."""
    from nice_tpu.client import api_client
    from nice_tpu.core.types import DataToServer, FieldSize
    from nice_tpu.ops import scalar

    results = scalar.process_range_detailed(
        FieldSize(data.range_start, data.range_end), data.base
    )
    payload = DataToServer(
        claim_id=data.claim_id,
        username="critpath-smoke",
        client_version="critpath-smoke",
        unique_distribution=list(results.distribution),
        nice_numbers=list(results.nice_numbers),
    )
    content = json.dumps(payload.to_json(), sort_keys=True).encode()
    payload.submit_id = (
        f"{data.claim_id}-{hashlib.sha256(content).hexdigest()[:16]}"
    )
    return api_client.submit_field_to_server(api_base, payload, max_retries=2)


def _client_worker(api_base: str, idx: int, results: list):
    try:
        claim = _claim(api_base)
        _submit(api_base, claim)
        results[idx] = {"field_ok": True, "claim_id": claim.claim_id}
    except Exception as exc:  # noqa: BLE001 — collected into failures
        results[idx] = {"error": repr(exc)}


def _wait_timelines_merged(api_base: str, field_ids: list, failures: list):
    """Block until every field's timeline shows canon plus the merged
    client round-trip events (delivered asynchronously via /telemetry)."""
    want = {"canon_promoted", "client_claim_rtt", "client_submit_rtt"}
    deadline = time.monotonic() + MERGE_WAIT_SECS
    pending = set(field_ids)
    while pending and time.monotonic() < deadline:
        for fid in sorted(pending):
            tl = _get(f"{api_base}/fields/{fid}/timeline")
            kinds = {e.get("kind") for e in tl.get("events", [])}
            if want <= kinds:
                pending.discard(fid)
        if pending:
            time.sleep(POLL_SECS)
    for fid in sorted(pending):
        failures.append(
            f"field {fid}: client events never merged into timeline"
        )


def _check_critpath(api_base: str, failures: list) -> dict:
    """Poll /critpath (2 s snapshot cache) until it covers all fields,
    then assert reconciliation + writer_wait dominance."""
    snap = {}
    deadline = time.monotonic() + MERGE_WAIT_SECS
    while time.monotonic() < deadline:
        snap = _get(f"{api_base}/critpath?fields={CLIENTS * 2}")
        if snap.get("fields", 0) >= CLIENTS:
            break
        time.sleep(POLL_SECS)
    if snap.get("fields", 0) != CLIENTS:
        failures.append(
            f"/critpath covered {snap.get('fields')} fields, "
            f"expected {CLIENTS}"
        )
        return snap
    if snap.get("unreconciled_fields"):
        failures.append(
            f"unreconciled fields: {snap['unreconciled_fields']}"
        )
    if snap.get("dominant") != "writer_wait":
        failures.append(
            f"fleet dominant segment is {snap.get('dominant')!r}, "
            "expected writer_wait (injected writer stall)"
        )
    for w in snap.get("waterfalls", []):
        fid = w.get("field_id")
        if not w.get("reconciled"):
            failures.append(
                f"field {fid}: waterfall residual {w.get('residual_secs')}s "
                f"exceeds tolerance {w.get('tolerance_secs')}s"
            )
        if w.get("dominant") != "writer_wait":
            failures.append(
                f"field {fid}: dominant {w.get('dominant')!r}, "
                "expected writer_wait"
            )
        ww = (w.get("segments") or {}).get("writer_wait", 0.0)
        if ww < STALL_SECS:
            failures.append(
                f"field {fid}: writer_wait {ww}s < one injected "
                f"stall ({STALL_SECS}s)"
            )
    return snap


def _check_metrics(api_base: str, failures: list):
    """The history tick (1 s cadence) must have published the share gauge."""
    share = None
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        for line in _get_text(f"{api_base}/metrics").splitlines():
            if line.startswith(
                'nice_critpath_segment_share{segment="writer_wait"}'
            ):
                try:
                    share = float(line.rsplit(None, 1)[-1])
                except ValueError:
                    share = None
        if share:
            return share
        time.sleep(POLL_SECS)
    failures.append(
        f"nice_critpath_segment_share{{writer_wait}} never went live "
        f"(last read: {share})"
    )
    return share


def _check_resume(api_base: str, failures: list) -> dict:
    """Reconnect from a mid-run cursor: the replay must carry every journal
    id after the cursor exactly once — the durable-cursor resume contract
    fleet.html's Last-Event-ID reconnects rely on."""
    feed = _get(f"{api_base}/events?since=0&limit=1000")
    all_ids = [e["id"] for e in feed.get("events", [])]
    if len(all_ids) < 4:
        failures.append(f"journal too short to test resume ({len(all_ids)})")
        return {"journal_rows": len(all_ids)}
    mid = all_ids[len(all_ids) // 2]
    expected = [i for i in all_ids if i > mid]
    probe = StreamProbe(
        f"{api_base}/events/stream?since={mid}",
        name="critpath-smoke-resume",
    )
    probe.start()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if len(probe.journal_ids()) >= len(expected):
            break
        time.sleep(POLL_SECS)
    probe.stop()
    probe.join(timeout=5)
    seen = probe.journal_ids()
    stats = {"cursor": mid, "expected": len(expected), "replayed": len(seen)}
    if len(seen) != len(set(seen)):
        failures.append(f"resume replayed duplicate journal ids: {seen}")
    if [i for i in seen if i <= mid]:
        failures.append(f"resume re-sent ids at/before cursor {mid}")
    missing = set(expected) - set(seen)
    if missing:
        failures.append(
            f"resume missed journal ids {sorted(missing)} after cursor {mid}"
        )
    return stats


def main() -> int:
    t_start = time.monotonic()
    if len(sys.argv) > 1:
        workdir = sys.argv[1]
        os.makedirs(workdir, exist_ok=True)
        cleanup = False
    else:
        workdir = tempfile.mkdtemp(prefix="critpath-smoke-")
        cleanup = True
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    env = dict(
        os.environ,
        NICE_TPU_SERVER_CORE="async",
        NICE_TPU_HISTORY_SECS="1",
        NICE_TPU_STREAM_HEARTBEAT_SECS="2",
        NICE_TPU_FAULTS=f"writer.batch:{STALL_SECS}",
    )
    db_path = os.path.join(workdir, "critpath.db")
    port = _pick_port()
    api_base = f"http://127.0.0.1:{port}"
    server_log = os.path.join(workdir, "server.log")
    server, logf = _start_server(db_path, port, server_log, env)

    failures: list = []
    line = {"workdir": workdir, "stall_secs": STALL_SECS}
    probe = StreamProbe(f"{api_base}/events/stream?since=0")
    try:
        if not _wait_listening(port, server):
            failures.append("server never listened")
            raise RuntimeError

        # Live probe first, seed second: everything the run journals from
        # here on must arrive over the stream as it happens, not via replay.
        probe.start()

        # Seed AFTER the server is up (WAL + busy_timeout make the
        # cross-process write safe; the claim path falls back to a direct
        # pool scan when the pre-claim queue was built before the seed).
        from nice_tpu.server.db import Db

        db = Db(db_path)
        db.seed_base(BASE, field_size=FIELD_SIZE)
        field_ids = [f.field_id for f in db.get_fields_in_base(BASE)]
        db.close()
        line["fields"] = len(field_ids)

        results: list = [None] * CLIENTS
        workers = [
            threading.Thread(
                target=_client_worker,
                args=(api_base, i, results),
                name=f"critpath-smoke-client-{i}",
            )
            for i in range(CLIENTS)
        ]
        for w in workers:
            w.start()
        for w in workers:
            w.join(timeout=120)
        for i, res in enumerate(results):
            if not res or "error" in res:
                failures.append(f"client {i} failed: {res}")
        if failures:
            raise RuntimeError

        # Deliver the buffered client trace events (claim/submit RTTs)
        # the same way a real client does: POST /telemetry.
        from nice_tpu.client import api_client
        from nice_tpu.obs import telemetry

        api_client.post_telemetry(
            api_base,
            telemetry.snapshot(
                username="critpath-smoke", client_version="critpath-smoke"
            ),
            max_retries=2,
        )

        _wait_timelines_merged(api_base, field_ids, failures)
        snap = _check_critpath(api_base, failures)
        line["critpath_dominant"] = snap.get("dominant")
        line["writer_wait_p50"] = (
            (snap.get("segments") or {}).get("writer_wait") or {}
        ).get("p50")
        line["writer_wait_share"] = _check_metrics(api_base, failures)

        # The live probe must have said hello and carried the run's
        # lifecycle as it happened (canon_promoted journaled after the
        # probe connected -> it arrived via push, not replay).
        if probe.error:
            failures.append(f"stream probe error: {probe.error}")
        if not probe.events("hello"):
            failures.append("stream never sent the hello frame")
        live_kinds = probe.journal_kinds()
        for kind in ("claimed", "submit_accepted", "canon_promoted"):
            if kind not in live_kinds:
                failures.append(
                    f"stream never carried a live {kind!r} journal event "
                    f"(saw {sorted(k for k in live_kinds if k)})"
                )
        line["stream"] = {
            "journal_events": len(probe.events("journal")),
            "heartbeats": probe.heartbeats,
            "kinds": sorted(k for k in live_kinds if k),
        }
        line["resume"] = _check_resume(api_base, failures)

        with open(os.path.join(workdir, "critpath.json"), "w") as f:
            json.dump(
                {
                    "base": BASE,
                    "stall_secs": STALL_SECS,
                    "critpath": snap,
                    "stream": line.get("stream"),
                    "resume": line.get("resume"),
                    "failures": failures,
                },
                f, indent=2,
            )
    except RuntimeError:
        pass
    except Exception as exc:  # noqa: BLE001 — smoke must always print
        failures.append(f"unexpected: {exc!r}")
    finally:
        probe.stop()
        if server.poll() is None:
            server.terminate()
            try:
                server.wait(timeout=15)
            except subprocess.TimeoutExpired:
                server.kill()
                server.wait(timeout=15)
        logf.close()
        probe.join(timeout=5)

    line["ok"] = not failures
    line["failures"] = failures
    line["elapsed_secs"] = round(time.monotonic() - t_start, 1)
    print(json.dumps(line), flush=True)
    if cleanup and not failures:
        shutil.rmtree(workdir, ignore_errors=True)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
