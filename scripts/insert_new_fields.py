#!/usr/bin/env python
"""Seed the coordination ledger with a base's chunks + fields (reference
scripts/insert_new_fields.rs).

Usage: python scripts/insert_new_fields.py --db nice.db --base 40 [--field-size 1000000000]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu.server.db import Db  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--db", default="nice.db")
    p.add_argument("--base", type=int, required=True, action="append")
    p.add_argument("--field-size", type=int, default=1_000_000_000)
    args = p.parse_args()
    db = Db(args.db)
    try:
        for base in args.base:
            n = db.seed_base(base, args.field_size)
            print(f"seeded base {base}: {n} fields")
    finally:
        db.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
