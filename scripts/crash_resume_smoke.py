"""Kill-resume smoke check for the checkpoint subsystem.

End-to-end crash drill against a real (in-process) API server and a real
client subprocess:

  1. seed a one-field base into a scratch DB and serve it on a loopback port;
  2. run the client with --checkpoint-dir and an aggressive snapshot cadence,
     wait for the first claim-*.ckpt to land, then SIGKILL it mid-scan;
  3. restart the same client command and let it run to completion.

Asserts that the second run resumed the SAME claim from the snapshot (no
re-claim), that the server accepted exactly one submission for it — the
submit path recomputes every nice number and checks the distribution total
against the field size, so acceptance proves the resumed scan is numerically
whole — that the submission matches a local scalar recomputation of the full
field, that the snapshot was retired after the confirmed submit, and that at
least one /renew_claim heartbeat landed. Prints ONE JSON line. Usage:

    python scripts/crash_resume_smoke.py [workdir]
    python scripts/crash_resume_smoke.py [workdir] --backend jnp --megaloop 2

The second form is the mid-megaloop drill: the client scans with the
device-resident lax.scan loop (NICE_TPU_MEGALOOP_SEGMENT pinned), so the
SIGKILL lands between segment dispatches and the resume must re-enter the
scan from a segment-granular snapshot.
"""

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE = 22  # full valid range [234256, 656395): ~1.5s of scalar work
FIELD_SIZE = 1_000_000  # one field spans the whole base range
POLL_SECS = 0.01
FIRST_SNAPSHOT_TIMEOUT = 60
RUN2_TIMEOUT = 180


def _client_cmd(api_base: str, ckpt_dir: str, backend: str) -> list:
    return [
        sys.executable, "-m", "nice_tpu.client", "detailed",
        "--api-base", api_base,
        "--checkpoint-dir", ckpt_dir,
        "--backend", backend,
        "--batch-size", "2048",
        "--checkpoint-secs", "0.05",
        "--renew-secs", "2",
        "--username", "crash-smoke",
    ]


def main() -> int:
    t_start = time.monotonic()
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("workdir", nargs="?", default=None)
    ap.add_argument("--backend", default="scalar",
                    help="client engine backend (scalar = host oracle; jnp "
                    "exercises the device dispatch loop)")
    ap.add_argument("--megaloop", default="",
                    help="pin NICE_TPU_MEGALOOP_SEGMENT for the client so "
                    "the SIGKILL lands between megaloop segments (device "
                    "backends only)")
    args = ap.parse_args()
    if args.workdir:
        workdir = args.workdir
        os.makedirs(workdir, exist_ok=True)
        cleanup = False
    else:
        workdir = tempfile.mkdtemp(prefix="crash-resume-smoke-")
        cleanup = True
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.megaloop:
        os.environ["NICE_TPU_MEGALOOP_SEGMENT"] = args.megaloop

    db_path = os.path.join(workdir, "smoke.db")
    ckpt_dir = os.path.join(workdir, "ckpt")

    from nice_tpu.ckpt import read_snapshot
    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import scalar
    from nice_tpu.server import app as server_app
    from nice_tpu.server.db import Db

    db = Db(db_path)
    db.seed_base(BASE, field_size=FIELD_SIZE)
    db.close()

    httpd = server_app.serve(db_path, host="127.0.0.1", port=0, prefill=False)
    port = httpd.server_address[1]
    api_base = f"http://127.0.0.1:{port}"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    failures = []
    line = {"workdir": workdir, "backend": args.backend}
    if args.megaloop:
        line["megaloop"] = int(args.megaloop)
    env = dict(os.environ)
    cmd = _client_cmd(api_base, ckpt_dir, args.backend)

    # -- run 1: scan until the first snapshot lands, then SIGKILL ----------
    log1_path = os.path.join(workdir, "run1.log")
    with open(log1_path, "wb") as log1:
        proc = subprocess.Popen(cmd, stdout=log1, stderr=subprocess.STDOUT, env=env)
        deadline = time.monotonic() + FIRST_SNAPSHOT_TIMEOUT
        snap_path = None
        while time.monotonic() < deadline:
            found = glob.glob(os.path.join(ckpt_dir, "claim-*.ckpt"))
            if found:
                snap_path = found[0]
                break
            if proc.poll() is not None:
                break
            time.sleep(POLL_SECS)
        if snap_path is None:
            failures.append(
                "no snapshot appeared before the client "
                f"{'exited' if proc.poll() is not None else 'timed out'}"
            )
        elif proc.poll() is not None:
            failures.append("client finished before it could be killed")
        else:
            time.sleep(0.2)  # let a few more snapshots land mid-scan
            proc.kill()  # SIGKILL: no atexit, no cleanup, a genuine crash
        proc.wait()

    if failures:
        line.update({"ok": False, "failures": failures})
        print(json.dumps(line), flush=True)
        return 1

    manifest, _ = read_snapshot(snap_path)
    claim_id = int(json.loads(json.dumps(manifest["field"]))["claim_id"])
    line["claim_id"] = claim_id
    line["kill_cursor"] = int(manifest["cursor"])
    field_rec = manifest["field"]

    db = Db(db_path)
    pre = db.get_detailed_submissions_by_field(
        db.get_claim_by_id(claim_id).field_id
    )
    if pre:
        failures.append(f"killed run somehow submitted ({len(pre)} submissions)")

    # -- run 2: same command; must resume, finish, and submit --------------
    log2_path = os.path.join(workdir, "run2.log")
    with open(log2_path, "wb") as log2:
        rc = subprocess.run(
            cmd, stdout=log2, stderr=subprocess.STDOUT, env=env,
            timeout=RUN2_TIMEOUT,
        ).returncode
    log2_text = open(log2_path, errors="replace").read()
    if rc != 0:
        failures.append(f"resumed run exited {rc}; tail: {log2_text[-2000:]}")
    if f"resuming claim {claim_id} from checkpoint" not in log2_text:
        failures.append("resumed run did not log a checkpoint resume")
    if glob.glob(os.path.join(ckpt_dir, "claim-*.ckpt")):
        failures.append("snapshot not retired after the confirmed submit")

    # -- verify the submission against a local recomputation ---------------
    claim = db.get_claim_by_id(claim_id)
    subs = db.get_detailed_submissions_by_field(claim.field_id)
    line["submissions"] = len(subs)
    if len(subs) != 1:
        failures.append(f"expected exactly 1 submission, found {len(subs)}")
    else:
        sub = subs[0]
        if sub.claim_id != claim_id:
            failures.append(
                f"submission belongs to claim {sub.claim_id}, expected "
                f"{claim_id} (client re-claimed instead of resuming)"
            )
        field = db.get_field_by_id(claim.field_id)
        ref = scalar.process_range_detailed(
            FieldSize(field.range_start, field.range_end), field.base
        )
        got_dist = {d.num_uniques: d.count for d in sub.distribution}
        ref_dist = {d.num_uniques: d.count for d in ref.distribution}
        if got_dist != ref_dist:
            failures.append("submitted distribution != scalar recomputation")
        got_nums = {(n.number, n.num_uniques) for n in sub.numbers}
        ref_nums = {(n.number, n.num_uniques) for n in ref.nice_numbers}
        if got_nums != ref_nums:
            failures.append("submitted nice numbers != scalar recomputation")
    db.close()

    # -- renewal heartbeat visible server-side -----------------------------
    with urllib.request.urlopen(f"{api_base}/metrics", timeout=10) as resp:
        metrics = resp.read().decode()
    renewals = 0.0
    for ln in metrics.splitlines():
        if ln.startswith("nice_server_claim_renewals_total"):
            renewals = float(ln.split()[-1])
    line["renewals"] = renewals
    if renewals < 1:
        failures.append("no /renew_claim heartbeat reached the server")

    httpd.shutdown()
    line["ok"] = not failures
    if failures:
        line["failures"] = failures
    line["elapsed_secs"] = round(time.monotonic() - t_start, 2)
    print(json.dumps(line), flush=True)
    if cleanup and not failures:
        shutil.rmtree(workdir, ignore_errors=True)
    return 0 if not failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
