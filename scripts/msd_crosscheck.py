#!/usr/bin/env python
"""Audit the C++ MSD prefix filter against the Python definition on random
ranges (reference scripts/msd_crosscheck.rs: fixed-width vs malachite audit).

Usage: python scripts/msd_crosscheck.py [--iters 500] [--seed 42]
"""

import argparse
import random
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu import native  # noqa: E402
from nice_tpu.core import base_range  # noqa: E402
from nice_tpu.core.types import FieldSize  # noqa: E402
from nice_tpu.ops import msd_filter  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=500)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--bases", type=int, nargs="*", default=[10, 17, 20, 40, 44, 50, 62, 80, 97])
    args = p.parse_args()

    if not native.available():
        print("native library unavailable; nothing to crosscheck", file=sys.stderr)
        return 1

    rng = random.Random(args.seed)
    checked = mismatches = 0
    for _ in range(args.iters):
        base = rng.choice(args.bases)
        r = base_range.get_base_range(base)
        span = r[1] - r[0]
        size = rng.choice([2, 10, 251, 4096, 100_000])
        if span <= size:
            continue
        start = r[0] + rng.randrange(span - size)
        fs = FieldSize(start, start + size)
        want = msd_filter.has_duplicate_msd_prefix(fs, base)
        got = native.has_duplicate_msd_prefix(fs.start(), fs.end(), base)
        checked += 1
        if got != want:
            mismatches += 1
            print(f"MISMATCH base={base} range=[{fs.start()},{fs.end()}): "
                  f"native={got} python={want}")
    print(f"checked {checked} ranges, {mismatches} mismatches")
    return 1 if mismatches else 0


if __name__ == "__main__":
    sys.exit(main())
