#!/usr/bin/env python
"""Brute-force scan of a base's whole range with the scalar oracle — no
filters (reference scripts/naive_base_search.rs). Ground truth for small bases.

Usage: python scripts/naive_base_search.py --base 10 [--limit 10000000]
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu.core import base_range  # noqa: E402
from nice_tpu.ops import scalar  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--base", type=int, default=10)
    p.add_argument("--limit", type=int, default=10_000_000,
                   help="refuse ranges bigger than this")
    args = p.parse_args()
    r = base_range.get_base_range(args.base)
    if r is None:
        print(f"base {args.base} has no valid range", file=sys.stderr)
        return 1
    size = r[1] - r[0]
    if size > args.limit:
        print(f"range size {size:.2e} exceeds --limit {args.limit:.2e}",
              file=sys.stderr)
        return 1
    t0 = time.monotonic()
    found = []
    for n in range(r[0], r[1]):
        if scalar.get_is_nice(n, args.base):
            found.append(n)
            print(f"nice: {n}")
    dt = time.monotonic() - t0
    print(f"base {args.base}: scanned {size} numbers in {dt:.2f}s "
          f"({size / dt:,.0f} n/s), {len(found)} nice")
    return 0


if __name__ == "__main__":
    sys.exit(main())
