#!/usr/bin/env python
"""Grouped bar chart of niceonly filter survival rates per base (reference
scripts/filter_effectiveness_chart.py, fed by filter_effectiveness.py output).

Reads the JSON lines produced by scripts/filter_effectiveness.py (one file or
its scripts/.cache directory) and renders survival-per-filter bars per base.
Lower is better: each bar is the fraction of candidates that SURVIVE that
filter alone.

Usage:
    python scripts/filter_effectiveness.py --base 40 > /tmp/fe40.json
    python scripts/filter_effectiveness.py --base 50 > /tmp/fe50.json
    python scripts/filter_effectiveness_chart.py /tmp/fe40.json /tmp/fe50.json \
        --out /tmp/filters.png
    python scripts/filter_effectiveness_chart.py --cache --out /tmp/filters.png
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

CACHE_DIR = Path(__file__).resolve().parent / ".cache"

# Filters in pipeline order; Okabe-Ito CVD-safe hues in fixed assignment.
FILTERS = (
    ("residue_survival", "residue (mod b-1)", "#0072B2"),
    ("lsd_survival", "LSD (mod b^k)", "#E69F00"),
    ("stride_survival", "CRT stride", "#009E73"),
    ("msd_survival", "MSD prefix", "#CC79A7"),
)


def load(paths: list[str], use_cache: bool) -> list[dict]:
    files = [Path(p) for p in paths]
    if use_cache:
        files += sorted(CACHE_DIR.glob("filter_effectiveness_*.json"))
    out = []
    for f in files:
        try:
            out.append(json.loads(f.read_text()))
        except (OSError, json.JSONDecodeError) as e:
            print(f"skipping {f}: {e}", file=sys.stderr)
    seen = {}
    for d in out:  # last measurement per base wins
        seen[d["base"]] = d
    return [seen[b] for b in sorted(seen)]


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("files", nargs="*", help="filter_effectiveness.py JSON outputs")
    p.add_argument("--cache", action="store_true",
                   help="also load everything under scripts/.cache")
    p.add_argument("--out", help="write PNG here (default: text table)")
    args = p.parse_args()

    data = load(args.files, args.cache)
    if not data:
        print(
            "no measurements; run scripts/filter_effectiveness.py first",
            file=sys.stderr,
        )
        return 1

    header = f"{'base':>5}" + "".join(f"{label:>18}" for _, label, _ in FILTERS)
    print(header + f"{'combined':>12}")
    for d in data:
        row = f"{d['base']:>5}"
        for key, _, _ in FILTERS:
            row += f"{100 * d[key]:>17.2f}%"
        print(row + f"{100 * d['combined_survival']:>11.3f}%")

    if not args.out:
        return 0

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    bases = [d["base"] for d in data]
    x = np.arange(len(bases), dtype=float)
    width = 0.8 / len(FILTERS)
    fig, ax = plt.subplots(figsize=(max(7, 2.2 * len(bases)), 4.5))
    for fi, (key, label, color) in enumerate(FILTERS):
        offs = (fi - (len(FILTERS) - 1) / 2) * width
        vals = [100 * d[key] for d in data]
        bars = ax.bar(x + offs, vals, width * 0.92, color=color, label=label)
        for rect, v in zip(bars, vals):
            ax.annotate(
                f"{v:.1f}", (rect.get_x() + rect.get_width() / 2, v),
                textcoords="offset points", xytext=(0, 2), ha="center",
                fontsize=7, color="#444444",
            )
    ax.set_xticks(x, [str(b) for b in bases])
    ax.set_xlabel("base")
    ax.set_ylabel("candidates surviving the filter (%)")
    ax.set_title("Niceonly filter survival per base (lower is better)")
    ax.legend(frameon=False, ncol=2)
    ax.grid(axis="y", color="#dddddd", linewidth=0.6)
    ax.set_axisbelow(True)
    for spine in ("top", "right"):
        ax.spines[spine].set_visible(False)
    fig.tight_layout()
    fig.savefig(args.out, dpi=140)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
