#!/usr/bin/env python3
"""nicelint CLI — project-invariant static analysis for nice_tpu.

Usage:
    python scripts/nicelint.py                 # report vs ratchet baseline
    python scripts/nicelint.py --strict        # CI gate: also fail stale
                                               # baseline entries
    python scripts/nicelint.py --update-baseline
    python scripts/nicelint.py --write-docs    # regenerate docs/KNOBS.md +
                                               # README knob tables
    python scripts/nicelint.py --json out.json # archive the full report
    python scripts/nicelint.py --rules W1,X1   # run a subset
    python scripts/nicelint.py --graph         # dump the static lock graph

Exit codes: 0 clean, 1 new violations (or stale baseline entries under
--strict), 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from nice_tpu.analysis import core  # noqa: E402
from nice_tpu.analysis.rules import k1_knobs, x1_lock_order  # noqa: E402
from nice_tpu.utils import knobs  # noqa: E402


def write_docs(root: str) -> list:
    """Regenerate docs/KNOBS.md and the README generated blocks; returns
    the list of files rewritten."""
    changed = []
    docs_dir = os.path.join(root, "docs")
    os.makedirs(docs_dir, exist_ok=True)
    knobs_md = os.path.join(docs_dir, "KNOBS.md")
    want = knobs.render_markdown()
    have = None
    if os.path.exists(knobs_md):
        with open(knobs_md, encoding="utf-8") as f:
            have = f.read()
    if have != want:
        with open(knobs_md, "w", encoding="utf-8") as f:  # nicelint: allow A1 (generated docs, not state)
            f.write(want)
        changed.append(os.path.relpath(knobs_md, root))
    readme = os.path.join(root, "README.md")
    if os.path.exists(readme):
        with open(readme, encoding="utf-8") as f:
            text = f.read()
        new_text = k1_knobs.rewrite_readme(text)
        if new_text != text:
            with open(readme, "w", encoding="utf-8") as f:  # nicelint: allow A1 (generated docs, not state)
                f.write(new_text)
            changed.append("README.md")
    return changed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=REPO_ROOT)
    ap.add_argument("--strict", action="store_true",
                    help="fail on stale baseline entries and docs drift")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the ratchet baseline to current findings")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate docs/KNOBS.md and README knob tables")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full violation report as JSON")
    ap.add_argument("--rules", metavar="IDS",
                    help="comma-separated rule subset (e.g. W1,X1)")
    ap.add_argument("--graph", action="store_true",
                    help="dump the static lock-order graph and exit")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root)
    project = core.Project(root)

    if args.write_docs:
        for rel in write_docs(root):
            print(f"nicelint: rewrote {rel}")

    if args.graph:
        graph = x1_lock_order.lock_graph(project)
        for outer in sorted(graph):
            for inner in sorted(graph[outer]):
                print(f"{outer} -> {inner}")
        return 0

    only = [r.strip().upper() for r in args.rules.split(",")] \
        if args.rules else None
    violations, used = core.run_rules_tracked(project, only=only)
    family = set(core.all_rules()) | {core.DEAD_SUPPRESSION_RULE}
    if only is None:
        # the dead-suppression audit (S1) needs every rule's usage data, so
        # it only runs on full (non --rules) invocations
        dead, _ = core.filter_allowed(
            project,
            core.dead_suppressions(project, set(core.all_rules()), used))
        violations = sorted(
            violations + dead,
            key=lambda v: (v.path, v.line, v.rule, v.detail))
    # the baseline file is shared with jaxlint's J-rule family — only this
    # family's slice is visible (and can go stale) here
    baseline = core.filter_baseline(core.load_baseline(root), family)
    if only:
        baseline = core.filter_baseline(baseline, set(only))
    new, stale = core.diff_against_baseline(violations, baseline)

    if args.update_baseline:
        old = core.load_baseline(root)
        entries = {k: v for k, v in old.items()
                   if k not in core.filter_baseline(old, family)}
        for v in violations:
            entries[v.key] = old.get(v.key, "TODO: justify or fix")
        core.save_baseline(root, entries)
        print(f"nicelint: baseline rewritten with {len(entries)} entries "
              f"({len(new)} new, {len(stale)} removed; other families "
              f"preserved)")
        return 0

    if args.json:
        report = {
            "violations": [v.to_json() for v in violations],
            "new": [v.to_json() for v in new],
            "stale_baseline_keys": stale,
            "baselined": len(violations) - len(new),
        }
        with open(args.json, "w", encoding="utf-8") as f:  # nicelint: allow A1 (CI artifact, not state)
            json.dump(report, f, indent=1)
            f.write("\n")

    for v in new:
        print(f"{v.path}:{v.line}: {v.rule}: {v.message}")
    if stale:
        print(f"nicelint: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (fixed violations "
              "still listed — run --update-baseline to burn them down):")
        for key in stale:
            print(f"  stale: {key}")

    baselined = len(violations) - len(new)
    print(f"nicelint: {len(new)} new, {baselined} baselined, "
          f"{len(stale)} stale")
    if new:
        return 1
    if args.strict and stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
