#!/usr/bin/env python
"""Search-progress charts from the coordination ledger (reference
scripts/progress_charts.py: submission history -> progress-over-time plots).

Renders two PNGs from the sqlite ledger:
  1. daily numbers searched, one line per search mode
  2. cumulative numbers searched over time per mode

With no --out, prints the daily totals as text instead.

Usage:
    python scripts/progress_charts.py --db nice.db --out /tmp/progress
"""

from __future__ import annotations

import argparse
import sys
from collections import defaultdict
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu.server.db import Db, unpad  # noqa: E402

# Okabe-Ito CVD-safe hues, fixed assignment: detailed is always blue,
# niceonly always orange (color follows the entity, never the rank).
MODE_COLORS = {"detailed": "#0072B2", "niceonly": "#E69F00"}
MODES = ("detailed", "niceonly")


def daily_totals(db: Db) -> dict[str, dict[str, int]]:
    """date -> mode -> numbers searched that day (disqualified excluded)."""
    with db._lock:
        rows = db._conn.execute(
            "SELECT s.submit_time, s.search_mode, f.range_size"
            " FROM submissions s JOIN fields f ON s.field_id = f.id"
            " WHERE s.disqualified = 0 ORDER BY s.submit_time ASC"
        ).fetchall()
    out: dict[str, dict[str, int]] = defaultdict(lambda: {m: 0 for m in MODES})
    for r in rows:
        out[r["submit_time"][:10]][r["search_mode"]] += unpad(r["range_size"])
    return dict(out)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--db", default="nice.db")
    p.add_argument("--out", help="output path prefix (writes <out>_daily.png"
                                 " and <out>_cumulative.png)")
    args = p.parse_args()

    db = Db(args.db)
    try:
        daily = daily_totals(db)
    finally:
        db.close()
    if not daily:
        print("no submissions in the ledger yet")
        return 0
    days = sorted(daily)

    if not args.out:
        print(f"{'date':>10} {'detailed':>16} {'niceonly':>16}")
        for d in days:
            print(f"{d:>10} {daily[d]['detailed']:>16} {daily[d]['niceonly']:>16}")
        return 0

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    def style(ax):
        ax.grid(axis="y", color="#dddddd", linewidth=0.6)
        ax.set_axisbelow(True)
        for spine in ("top", "right"):
            ax.spines[spine].set_visible(False)
        ax.tick_params(axis="x", rotation=45)

    # 1) daily totals per mode (two series -> legend present)
    fig, ax = plt.subplots(figsize=(9, 4.5))
    for mode in MODES:
        ax.plot(
            days, [daily[d][mode] for d in days],
            color=MODE_COLORS[mode], linewidth=2, marker="o", markersize=4,
            label=mode,
        )
    ax.set_ylabel("numbers searched per day")
    ax.set_title("Daily search volume")
    ax.legend(frameon=False)
    style(ax)
    fig.tight_layout()
    daily_path = f"{args.out}_daily.png"
    fig.savefig(daily_path, dpi=140)
    print(f"wrote {daily_path}")

    # 2) cumulative totals per mode
    fig, ax = plt.subplots(figsize=(9, 4.5))
    for mode in MODES:
        run, series = 0, []
        for d in days:
            run += daily[d][mode]
            series.append(run)
        ax.plot(
            days, series, color=MODE_COLORS[mode], linewidth=2, label=mode
        )
    ax.set_ylabel("cumulative numbers searched")
    ax.set_title("Search progress over time")
    ax.legend(frameon=False)
    style(ax)
    fig.tight_layout()
    cum_path = f"{args.out}_cumulative.png"
    fig.savefig(cum_path, dpi=140)
    print(f"wrote {cum_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
