#!/usr/bin/env python
"""Search-progress charts from the ledger or the live /history endpoint
(reference scripts/progress_charts.py: submission history ->
progress-over-time plots).

Two sources:

  --db nice.db        legacy path: daily totals from the sqlite ledger,
                      rendered as PNGs (--out prefix) or printed as text.
  --url http://host:port
                      live path: pulls the observatory time-series
                      (GET /history, obs/history.py) and emits the chart
                      JSON web/fleet.html's search-progress pane consumes
                      (--out <file.json>, default web/progress_chart.json).

Usage:
    python scripts/progress_charts.py --db nice.db --out /tmp/progress
    python scripts/progress_charts.py --url http://localhost:8089 \\
        --out web/progress_chart.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.parse
import urllib.request
from collections import defaultdict
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# Okabe-Ito CVD-safe hues, fixed assignment: detailed is always blue,
# niceonly always orange (color follows the entity, never the rank).
MODE_COLORS = {"detailed": "#0072B2", "niceonly": "#E69F00"}
MODES = ("detailed", "niceonly")

# The /history series behind the live search-progress pane: cumulative
# numbers searched, the instantaneous fleet rate, and fields completed
# per mode (labels keep MODE_COLORS meaningful).
PROGRESS_SERIES = (
    "nice_fleet_numbers",
    "nice_fleet_numbers_per_sec",
    'nice_fleet_fields_total{mode="detailed"}',
    'nice_fleet_fields_total{mode="niceonly"}',
)


def fetch_history(url: str, series=PROGRESS_SERIES, since: float = 0.0,
                  timeout: float = 10.0) -> dict:
    """GET /history for the progress series; tolerates absent series (a
    young server may not have sampled them yet)."""
    q = urllib.parse.urlencode(
        {"series": ",".join(series), "since": since}
    )
    req = urllib.request.Request(f"{url.rstrip('/')}/history?{q}")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        if e.code == 404:
            # Unknown series: fall back to one-by-one so the known subset
            # still charts.
            out: dict = {"series": {}}
            for s in series:
                q1 = urllib.parse.urlencode({"series": s, "since": since})
                try:
                    with urllib.request.urlopen(
                        urllib.request.Request(
                            f"{url.rstrip('/')}/history?{q1}"
                        ),
                        timeout=timeout,
                    ) as resp:
                        out["series"].update(
                            json.loads(resp.read().decode("utf-8")).get(
                                "series", {}
                            )
                        )
                except urllib.error.HTTPError:
                    continue
            return out
        raise


def chart_json(history: dict, source: str) -> dict:
    """The wire format web/fleet.html's progress pane reads: per-series
    multi-tier points plus the fixed mode palette."""
    return {
        "v": 1,
        "generated_ts": time.time(),
        "source": source,
        "colors": MODE_COLORS,
        "series": history.get("series", {}),
    }


def daily_totals(db) -> dict[str, dict[str, int]]:
    """date -> mode -> numbers searched that day (disqualified excluded)."""
    from nice_tpu.server.db import unpad

    with db._lock:
        rows = db._conn.execute(
            "SELECT s.submit_time, s.search_mode, f.range_size"
            " FROM submissions s JOIN fields f ON s.field_id = f.id"
            " WHERE s.disqualified = 0 ORDER BY s.submit_time ASC"
        ).fetchall()
    out: dict[str, dict[str, int]] = defaultdict(lambda: {m: 0 for m in MODES})
    for r in rows:
        out[r["submit_time"][:10]][r["search_mode"]] += unpad(r["range_size"])
    return dict(out)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--db", default="nice.db")
    p.add_argument("--url", default=None,
                   help="server base URL; switches to the live /history "
                        "source and JSON output")
    p.add_argument("--out", help="PNG path prefix (--db mode) or chart JSON "
                                 "path (--url mode; default "
                                 "web/progress_chart.json)")
    p.add_argument("--since", type=float, default=0.0,
                   help="--url mode: only points at/after this unix ts")
    args = p.parse_args()

    if args.url:
        history = fetch_history(args.url, since=args.since)
        chart = chart_json(history, f"{args.url.rstrip('/')}/history")
        out_path = Path(args.out or "web/progress_chart.json")
        out_path.parent.mkdir(parents=True, exist_ok=True)
        out_path.write_text(json.dumps(chart, indent=1, sort_keys=True))
        n_pts = sum(
            len(pts)
            for tiers in chart["series"].values()
            for pts in tiers.values()
        )
        print(f"wrote {out_path} ({len(chart['series'])} series, "
              f"{n_pts} points)")
        return 0

    from nice_tpu.server.db import Db

    db = Db(args.db)
    try:
        daily = daily_totals(db)
    finally:
        db.close()
    if not daily:
        print("no submissions in the ledger yet")
        return 0
    days = sorted(daily)

    if not args.out:
        print(f"{'date':>10} {'detailed':>16} {'niceonly':>16}")
        for d in days:
            print(f"{d:>10} {daily[d]['detailed']:>16} {daily[d]['niceonly']:>16}")
        return 0

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    def style(ax):
        ax.grid(axis="y", color="#dddddd", linewidth=0.6)
        ax.set_axisbelow(True)
        for spine in ("top", "right"):
            ax.spines[spine].set_visible(False)
        ax.tick_params(axis="x", rotation=45)

    # 1) daily totals per mode (two series -> legend present)
    fig, ax = plt.subplots(figsize=(9, 4.5))
    for mode in MODES:
        ax.plot(
            days, [daily[d][mode] for d in days],
            color=MODE_COLORS[mode], linewidth=2, marker="o", markersize=4,
            label=mode,
        )
    ax.set_ylabel("numbers searched per day")
    ax.set_title("Daily search volume")
    ax.legend(frameon=False)
    style(ax)
    fig.tight_layout()
    daily_path = f"{args.out}_daily.png"
    fig.savefig(daily_path, dpi=140)
    print(f"wrote {daily_path}")

    # 2) cumulative totals per mode
    fig, ax = plt.subplots(figsize=(9, 4.5))
    for mode in MODES:
        run, series = 0, []
        for d in days:
            run += daily[d][mode]
            series.append(run)
        ax.plot(
            days, series, color=MODE_COLORS[mode], linewidth=2, label=mode
        )
    ax.set_ylabel("cumulative numbers searched")
    ax.set_title("Search progress over time")
    ax.legend(frameon=False)
    style(ax)
    fig.tight_layout()
    cum_path = f"{args.out}_cumulative.png"
    fig.savefig(cum_path, dpi=140)
    print(f"wrote {cum_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
