// Execute the REAL browser worker (web/search/worker.js) under node and
// check it against an independent BigInt oracle — the in-repo Python twin
// (tests/test_worker_twin.py) pins the algorithm, but only this harness
// proves the shipped JS itself runs and agrees (VERDICT r4: the worker had
// never executed during judging; GitHub runners have node, so CI runs this).
//
// Usage: node scripts/check_worker_node.mjs

import { readFileSync } from "node:fs";
import { fileURLToPath } from "node:url";
import { dirname, join } from "node:path";

const here = dirname(fileURLToPath(import.meta.url));
const src = readFileSync(join(here, "..", "web", "search", "worker.js"), "utf8");

// Worker-global shims. worker.js is strict-mode and assigns the bare name
// `onmessage`; predefining the property makes that a resolvable binding.
globalThis.onmessage = null;
const outbox = [];
globalThis.postMessage = (m) => outbox.push(m);
(0, eval)(src);
if (typeof globalThis.onmessage !== "function") {
  console.error("worker.js did not install onmessage");
  process.exit(1);
}

// Independent oracle (deliberately NOT the worker's own BigInt fallback).
function oracleUniques(n, base) {
  const digits = new Set();
  for (let v = n * n; v > 0n; v /= base) digits.add(Number(v % base));
  for (let v = n * n * n; v > 0n; v /= base) digits.add(Number(v % base));
  return digits.size;
}

function runWorker(start, end, base) {
  outbox.length = 0;
  globalThis.onmessage({
    data: { type: "process", start: start.toString(), end: end.toString(), base },
  });
  const done = outbox.find((m) => m.type === "complete");
  const err = outbox.find((m) => m.type === "error");
  if (err) throw new Error(`worker error: ${err.message}`);
  if (!done) throw new Error("worker produced no complete message");
  return done.result;
}

let failures = 0;
function check(cond, label) {
  if (!cond) {
    failures++;
    console.error(`FAIL: ${label}`);
  } else {
    console.log(`ok: ${label}`);
  }
}

// 1) b10 golden: the full base-10 range [47, 100) contains exactly one nice
//    number, 69 (reference README's canonical example).
{
  const r = runWorker(47n, 100n, 10);
  check(r.engine === "fast", "b10 uses the fast engine");
  check(
    r.nice_numbers.some((n) => n.number === "69" && n.num_uniques === 10),
    "b10 finds 69"
  );
  check(
    r.nice_numbers.filter((n) => n.num_uniques === 10).length === 1,
    "b10 finds exactly one nice number"
  );
}

// 2) b40 and b50 slices: distribution and near-miss list vs the oracle.
//    Range starts from nice_tpu.core.base_range (committed constants).
const SLICES = [
  { base: 40, start: 1916284264916n, count: 4000 },
  { base: 50, start: 26507984537059635n, count: 2000 },
];
for (const { base, start, count } of SLICES) {
  const r = runWorker(start, start + BigInt(count), base);
  check(r.engine === "fast", `b${base} uses the fast engine`);
  const dist = {};
  for (let u = 1; u <= base; u++) dist[u] = 0;
  const cutoff = Math.floor(0.9 * base);
  const misses = [];
  for (let i = 0n; i < BigInt(count); i++) {
    const n = start + i;
    const u = oracleUniques(n, BigInt(base));
    dist[u]++;
    if (u > cutoff) misses.push(`${n}:${u}`);
  }
  const total = Object.values(r.distribution).reduce((a, b) => a + b, 0);
  check(total === count, `b${base} distribution covers the slice`);
  check(
    JSON.stringify(r.distribution) === JSON.stringify(dist),
    `b${base} distribution matches oracle`
  );
  const got = r.nice_numbers.map((n) => `${n.number}:${n.num_uniques}`);
  check(
    JSON.stringify(got) === JSON.stringify(misses),
    `b${base} near-miss list matches oracle`
  );
}

// 3) progress accounting: chunked progress messages must sum to the total.
{
  outbox.length = 0;
  globalThis.onmessage({
    data: {
      type: "process",
      start: "1916284264916",
      end: "1916284464916",
      base: 40,
    },
  });
  const progressed = outbox
    .filter((m) => m.type === "progress")
    .reduce((a, m) => a + Number(m.processed), 0);
  check(progressed === 200000, "progress messages sum to the field size");
}

process.exit(failures === 0 ? 0 : 1);
