#!/usr/bin/env python
"""Racecheck smoke: drive every schedex scenario and verify its verdict.

For each scenario in nice_tpu/analysis/scenarios.py the explorer runs the
FIFO baseline, the k<=2 systematic preemption schedules (capped by
NICE_TPU_SCHEDEX_MAX_SCHEDULES), and NICE_TPU_SCHEDEX_SEEDS seeded random
schedules.  A scenario with ``expect = "pass"`` must hold its invariant on
EVERY schedule; an ``expect = "race"`` twin must be caught on at least one
schedule within the bound — and that failing schedule is then replayed from
its id alone to prove byte-for-byte determinism.

Also emits the zero-cost line: with NICE_TPU_SCHEDEX unset/0 no lockdep
factory hook is installed, so ``lockdep.make_lock`` must hand out plain
``threading.Lock`` objects at plain-lock speed — measured A/B against a raw
threading.Lock and reported as a BENCH-comparable line in the JSON report.

Exits nonzero (listing the mismatches) if any verdict diverges, if a replay
is not trace-identical, or if the schedex-off path is not hook-free.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu.analysis import scenarios as scen_mod  # noqa: E402
from nice_tpu.analysis import schedex  # noqa: E402
from nice_tpu.utils import knobs, lockdep  # noqa: E402


def _bench_schedex_off(iters: int = 50_000) -> dict:
    """Time `with lock: pass` for a raw threading.Lock vs. one minted by
    lockdep.make_lock with schedex off — the ratio must be ~1x because no
    wrapper may be installed on the production path."""
    import threading

    hook_installed = lockdep.factory_hook() is not None
    minted = lockdep.make_lock("racecheck.bench")
    raw = threading.Lock()

    def _time(lock) -> float:
        t0 = time.perf_counter()
        for _ in range(iters):
            with lock:
                pass
        return time.perf_counter() - t0

    _time(raw)  # warm
    raw_s = _time(raw)
    minted_s = _time(minted)
    return {
        "iters": iters,
        "hook_installed": hook_installed,
        "minted_type": type(minted).__name__,
        "raw_ns_per_op": raw_s / iters * 1e9,
        "minted_ns_per_op": minted_s / iters * 1e9,
        "ratio": (minted_s / raw_s) if raw_s else None,
    }


def run(only: list[str] | None, seeds: int, preemptions: int,
        max_schedules: int, json_path: str | None,
        verbose: bool) -> int:
    names = only or sorted(scen_mod.SCENARIOS)
    unknown = [n for n in names if n not in scen_mod.SCENARIOS]
    if unknown:
        print(f"racecheck: unknown scenarios {unknown}; "
              f"known: {sorted(scen_mod.SCENARIOS)}", file=sys.stderr)
        return 2

    problems: list[str] = []
    report: dict = {"scenarios": {}, "knobs": {
        "seeds": seeds, "preemptions": preemptions,
        "max_schedules": max_schedules,
    }}

    for name in names:
        cls = scen_mod.SCENARIOS[name]
        t0 = time.perf_counter()
        rep = schedex.explore(
            cls, seeds=seeds, preemptions=preemptions,
            max_schedules=max_schedules,
            stop_on_failure=(cls.expect == "race"))
        elapsed = time.perf_counter() - t0
        entry = rep.as_dict()
        entry["expect"] = cls.expect
        entry["elapsed_s"] = round(elapsed, 3)

        caught = not rep.ok
        if cls.expect == "pass" and caught:
            first = rep.first_failing()
            problems.append(
                f"{name}: expected PASS but schedule {first.schedule_id} "
                f"broke the invariant: {first.failures}")
            entry["verdict"] = "UNEXPECTED-RACE"
            entry["failing"][0]["trace"] = [
                list(t) for t in first.trace]
        elif cls.expect == "race" and not caught:
            problems.append(
                f"{name}: expected the explorer to catch the race within "
                f"{rep.schedules_run} schedules (k<={preemptions}), but "
                f"every schedule passed")
            entry["verdict"] = "RACE-MISSED"
        else:
            entry["verdict"] = "OK"

        # Determinism: replay the first failing schedule from its id and
        # demand the identical trace.
        if caught:
            first = rep.first_failing()
            replayed = schedex.replay(cls, first.schedule_id)
            entry["replay"] = {
                "schedule": first.schedule_id,
                "trace_identical": replayed.trace == first.trace,
                "still_failing": not replayed.ok,
            }
            entry.setdefault("failing", [])
            if entry["failing"]:
                entry["failing"][0]["trace"] = [list(t) for t in first.trace]
            if replayed.trace != first.trace or replayed.ok:
                problems.append(
                    f"{name}: replay of {first.schedule_id} diverged "
                    f"(trace_identical={replayed.trace == first.trace}, "
                    f"still_failing={not replayed.ok})")
                entry["verdict"] = "REPLAY-DIVERGED"

        report["scenarios"][name] = entry
        status = entry["verdict"]
        detail = (f"caught by {rep.first_failing().schedule_id}" if caught
                  else "all schedules held")
        print(f"racecheck: {name:<38} expect={cls.expect:<5} "
              f"schedules={rep.schedules_run:<4} {status} ({detail}, "
              f"{elapsed:.2f}s)")
        if verbose and caught:
            for step, thread, point in rep.first_failing().trace:
                print(f"    [{step:3d}] {thread:<16} {point}")

    bench = _bench_schedex_off()
    report["bench_schedex_off"] = bench
    print(f"BENCH racecheck schedex_off_lock_overhead: "
          f"raw={bench['raw_ns_per_op']:.0f}ns/op "
          f"minted={bench['minted_ns_per_op']:.0f}ns/op "
          f"ratio={bench['ratio']:.2f} "
          f"minted_type={bench['minted_type']} "
          f"hook_installed={bench['hook_installed']}")
    if bench["hook_installed"]:
        problems.append(
            "schedex-off path is not clean: a lockdep factory hook is "
            "installed outside any instrument() window")
    if bench["minted_type"] not in ("lock", "Lock"):
        problems.append(
            f"schedex-off make_lock minted a {bench['minted_type']}, "
            f"expected a plain threading.Lock")

    report["ok"] = not problems
    report["problems"] = problems
    if json_path:
        Path(json_path).write_text(json.dumps(report, indent=1) + "\n")
        print(f"racecheck: wrote {json_path}")

    if problems:
        print("racecheck: FAIL", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(f"racecheck: OK ({len(names)} scenarios)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--only", action="append", default=None, metavar="NAME",
                    help="run just this scenario (repeatable)")
    ap.add_argument("--seeds", type=int,
                    default=int(knobs.SCHEDEX_SEEDS.get()))
    ap.add_argument("--preemptions", type=int,
                    default=int(knobs.SCHEDEX_PREEMPTIONS.get()))
    ap.add_argument("--max-schedules", type=int,
                    default=int(knobs.SCHEDEX_MAX_SCHEDULES.get()))
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the machine-readable report here")
    ap.add_argument("--verbose", action="store_true",
                    help="print the failing trace for caught races")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    args = ap.parse_args(argv)
    if args.list:
        for name, cls in sorted(scen_mod.SCENARIOS.items()):
            print(f"{name:<38} expect={cls.expect}")
        return 0
    return run(args.only, args.seeds, args.preemptions,
               args.max_schedules, args.json, args.verbose)


if __name__ == "__main__":
    sys.exit(main())
