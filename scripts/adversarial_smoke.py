"""Adversarial client harness: hostile personas against a real server.

Runs the same server twice on identically seeded ledgers:

  phase 1 (baseline)    honest honor-system clients only
  phase 2 (adversarial) the same honest population PLUS four personas:
    result-forger   submits fabricated nice numbers (niceonly) and a
                    fabricated distribution (detailed)
    claim-hoarder   claims micro-field blocks and walks away (abandons)
    replayer        re-sends an already-accepted submission verbatim
    rate-flooder    hammers /claim under one client token

Both phases end with a drain loop that completes every remaining field, then
the harness audits the ledger and asserts the hardening contract:

  * forged results are 100% disqualified and 0% canon
  * every abandoned field is re-issued (lease sweep) and completed
  * the flooder gets 429s while honest clients see none and keep their
    submit p99 within 2x of the baseline phase
  * replays are exactly-once (no submit_id ever has two rows)
  * the adversarial ledger digest is byte-identical to the honest baseline
    (field ranges + clamped check level + live submission content)

Usage:
    python scripts/adversarial_smoke.py --out ADVERSARIAL_r01.json
    python scripts/adversarial_smoke.py --honest 8 --fields 200   # CI scale

Exit code 0 only when every assertion holds.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import os
import socket
import sqlite3
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from load_harness import (  # noqa: E402
    BASE,
    MiniConn,
    Stats,
    _pctl,
    _pick_port,
    _seed_db,
    _submission,
)

from nice_tpu import faults  # noqa: E402

# The hardening envelope under test — identical for both phases so the
# ledgers are comparable. Seeded spot verification samples 100% (fresh,
# untrusted clients) with a pinned RNG seed; micro-leases expire in 1s and
# the writer-actor sweep re-issues them ~4x/sec; the per-client rate buckets
# are sized so the sequential honest/drain loops never trip them but a
# tight-loop flooder does.
SERVER_ENV = {
    "NICE_TPU_TRUST_THRESHOLD": "5",
    "NICE_TPU_SPOT_RATE": "1.0",
    "NICE_TPU_SPOT_SEED": "1",
    "NICE_TPU_SPOT_SLICE": "256",
    "NICE_TPU_UNTRUSTED_LEASE_SECS": "1",
    "NICE_TPU_LEASE_SWEEP_SECS": "0.25",
    "NICE_TPU_UNTRUSTED_MAX_CLAIMS": "16",
    "NICE_TPU_UNTRUSTED_MAX_CLAIMS_PER_IP": "256",
    "NICE_TPU_RATE_BUCKET": "200:60",
    "NICE_TPU_MAX_INFLIGHT": "1024",
    "NICE_TPU_SERVER_WORKERS": "16",
    "JAX_PLATFORMS": "cpu",
}
DEFAULT_FAULT_SPEC = "http.submit_block:drop_response@0.05"
DEFAULT_FAULT_SEED = 1


def _spawn_server(db_path: str, workdir: str):
    port = _pick_port()
    env = dict(os.environ, **SERVER_ENV)
    env.pop("NICE_TPU_FAULTS", None)  # faults live client-side here
    logf = open(os.path.join(workdir, "server.log"), "ab")
    server = subprocess.Popen(
        [
            sys.executable, "-m", "nice_tpu.server",
            "--db", db_path, "--host", "127.0.0.1", "--port", str(port),
        ],
        stdout=logf, stderr=subprocess.STDOUT, env=env,
    )
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if server.poll() is not None:
            raise RuntimeError("server subprocess died on startup")
        try:
            with socket.create_connection(("127.0.0.1", port), 1):
                break
        except OSError:
            time.sleep(0.05)
    else:
        raise RuntimeError("server never started listening")
    return server, port, logf


async def _req(conn: MiniConn, token: str, method: str, target: str,
               body=None, attempts: int = 4):
    """One request under a client token, with bounded replay on faults and
    transport errors (mirrors load_harness._faulted_request)."""
    endpoint = target.lstrip("/").split("/", 1)[0].split("?", 1)[0]
    headers = {"X-Client-Token": token}
    for _ in range(attempts):
        act = faults.fire(f"http.{endpoint}", target=target)
        try:
            if act == "drop_response":
                await conn.request(method, target, body, headers=headers)
                continue  # the reply vanished; replay
            if act in ("conn_error", "raise"):
                continue
            return await conn.request(method, target, body, headers=headers)
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            continue
    return None, None


async def _mint_token(conn: MiniConn, fallback: str) -> str:
    """Register a server-issued trust token (POST /token). The server only
    honors tokens it minted — arbitrary bearer strings fall back to the
    ip-keyed identity — so every persona registers one real token up front.
    The static name is only a last resort against a dead server."""
    try:
        status, body = await conn.request("POST", "/token", None)
    except OSError:
        return fallback
    if status == 200 and isinstance(body, dict) and body.get("client_token"):
        return body["client_token"]
    return fallback


# -- personas ----------------------------------------------------------------


async def _honest_client(cfg, stats: Stats, idx: int):
    """The load_harness honor-system loop, under a per-client trust token.
    Also the control group for the p99 and zero-429 assertions."""
    name = f"honest-{idx}"
    conn = MiniConn(cfg["host"], cfg["port"])
    token = await _mint_token(conn, name)
    try:
        for _ in range(cfg["rounds"]):
            t0 = time.monotonic()
            status, block = await _req(
                conn, token, "POST", "/claim_block",
                {"mode": "niceonly", "count": cfg["block_size"],
                 "username": name},
            )
            stats.claim_lat.append(time.monotonic() - t0)
            if status == 429:
                stats.honest_429s += 1  # honest 429s fail the run
                continue
            if status != 200:
                continue  # claim exhaustion near the end of the frontier
            subs = [
                _submission(f["claim_id"], name) for f in block["fields"]
            ]
            stats.fields_claimed += len(subs)
            t0 = time.monotonic()
            status, resp = await _req(
                conn, token, "POST", "/submit_block",
                {"block_id": block["block_id"], "submissions": subs},
            )
            stats.submit_lat.append(time.monotonic() - t0)
            if status == 429:
                stats.honest_429s += 1
            elif status == 200 and isinstance(resp, dict):
                for result in resp.get("results") or []:
                    if not isinstance(result, dict):
                        continue
                    if result.get("status") == "error":
                        stats.http_errors += 1
                    elif result.get("duplicate"):
                        stats.duplicates += 1
                    else:
                        stats.submissions_accepted += 1
                stats.owned_submit_ids.extend(s["submit_id"] for s in subs)
    finally:
        await conn.close()


async def _forger(cfg, out: dict):
    """Result forger: fabricated niceonly numbers + a fabricated detailed
    distribution, all of which pass the accept-time shape checks."""
    conn = MiniConn(cfg["host"], cfg["port"])
    token = await _mint_token(conn, "forger")
    out["forger_token"] = token
    forged = 0
    try:
        for _ in range(cfg["forgeries"]):
            status, field = await _req(
                conn, token, "GET", "/claim/niceonly?username=forger"
            )
            if status != 200:
                continue
            # Claims the field's first number is 100% nice — the
            # trusted-engine recompute in the spot check disproves it.
            payload = {
                "claim_id": field["claim_id"],
                "username": "forger",
                "client_version": "adversarial",
                "unique_distribution": None,
                "nice_numbers": [
                    {"number": int(field["range_start"]), "num_uniques": BASE}
                ],
            }
            status, _ = await _req(conn, token, "POST", "/submit", payload)
            forged += status == 200
        for _ in range(cfg["detailed_forgeries"]):
            status, field = await _req(
                conn, token, "GET", "/claim/detailed?username=forger"
            )
            if status != 200:
                continue
            # All mass claimed in one low bucket: sums match, no numbers due
            # above the cutoff — shape-valid, and refuted by any real slice.
            payload = {
                "claim_id": field["claim_id"],
                "username": "forger",
                "client_version": "adversarial",
                "unique_distribution": [
                    {"num_uniques": 1, "count": int(field["range_size"])}
                ],
                "nice_numbers": [],
            }
            status, _ = await _req(conn, token, "POST", "/submit", payload)
            forged += status == 200
    finally:
        await conn.close()
    out["forged_accepted"] = forged


async def _hoarder(cfg, out: dict):
    """Claim hoarder/abandoner: grabs micro-field blocks, never submits.
    The outstanding-claims cap 429s further hoarding; the lease sweep
    re-issues everything it sat on."""
    conn = MiniConn(cfg["host"], cfg["port"])
    token = await _mint_token(conn, "hoarder")
    abandoned: list[str] = []
    capped = 0
    try:
        for _ in range(8):
            status, block = await _req(
                conn, token, "POST", "/claim_block",
                {"mode": "niceonly", "count": 8, "username": "hoarder"},
            )
            if status == 429:
                capped += 1
                break
            if status == 200:
                abandoned.extend(f["range_start"] for f in block["fields"])
    finally:
        await conn.close()
    out["abandoned_fields"] = abandoned
    out["hoarder_hit_cap"] = capped > 0


async def _replayer(cfg, out: dict):
    """Replays one accepted submission verbatim: every replay must answer
    {"duplicate": true} and mint no second row."""
    conn = MiniConn(cfg["host"], cfg["port"])
    token = await _mint_token(conn, "replayer")
    duplicates = 0
    try:
        status, field = await _req(
            conn, token, "GET", "/claim/niceonly?username=replayer"
        )
        if status == 200:
            sub = _submission(field["claim_id"], "replayer")
            await _req(conn, token, "POST", "/submit", sub)
            for _ in range(5):
                status, resp = await _req(
                    conn, token, "POST", "/submit", sub
                )
                duplicates += bool(
                    status == 200 and isinstance(resp, dict)
                    and resp.get("duplicate")
                )
    finally:
        await conn.close()
    out["replay_duplicates"] = duplicates


async def _flooder(cfg, out: dict):
    """Rate flooder: a tight claim loop under one token. The per-client
    bucket 429s it without touching anyone else's budget."""
    conn = MiniConn(cfg["host"], cfg["port"])
    token = await _mint_token(conn, "flooder")
    limited = sent = 0
    try:
        for _ in range(cfg["flood_requests"]):
            status, _ = await _req(
                conn, token, "GET", "/claim/niceonly?username=flooder",
                attempts=1,
            )
            sent += status is not None
            limited += status == 429
    finally:
        await conn.close()
    out["flood_requests"] = sent
    out["flood_429s"] = limited


# -- drain + ledger audits ---------------------------------------------------


def _incomplete_fields(db_path: str) -> int:
    conn = sqlite3.connect(db_path)
    try:
        return conn.execute(
            "SELECT COUNT(*) FROM fields f WHERE NOT EXISTS"
            " (SELECT 1 FROM submissions s WHERE s.field_id = f.id"
            "  AND s.disqualified = 0)"
        ).fetchone()[0]
    finally:
        conn.close()


async def _drain(cfg, db_path: str, deadline_secs: float = 90.0) -> int:
    """Complete every remaining field (re-issued abandons surface as their
    short leases expire). Returns fields left incomplete at the deadline."""
    conn = MiniConn(cfg["host"], cfg["port"])
    token = await _mint_token(conn, "drain")
    deadline = time.monotonic() + deadline_secs
    try:
        while time.monotonic() < deadline:
            remaining = _incomplete_fields(db_path)
            if remaining == 0:
                return 0
            status, block = await _req(
                conn, token, "POST", "/claim_block",
                {"mode": "niceonly", "count": 12, "username": "drain"},
            )
            if status != 200:
                # Exhausted = everything claimable is leased out; wait for
                # the sweep to recycle abandoned micro-leases.
                await asyncio.sleep(0.3)
                continue
            subs = [
                _submission(f["claim_id"], "drain") for f in block["fields"]
            ]
            await _req(
                conn, token, "POST", "/submit_block",
                {"block_id": block["block_id"], "submissions": subs},
            )
        return _incomplete_fields(db_path)
    finally:
        await conn.close()


def _ledger_digest(db_path: str) -> str:
    """Content digest of the canonical ledger: per field, the range bounds,
    the check level clamped to [0,1] (re-verification churn is not
    corruption), and the SORTED DISTINCT content of live submissions.
    Usernames, ips, timestamps, claims, and disqualified rows are all
    excluded — two runs that established the same canonical knowledge hash
    identically."""
    conn = sqlite3.connect(db_path)
    conn.row_factory = sqlite3.Row
    try:
        fields = conn.execute(
            "SELECT id, range_start, range_end, check_level FROM fields"
            " ORDER BY range_start"
        ).fetchall()
        subs: dict[int, set] = {}
        for row in conn.execute(
            "SELECT field_id, search_mode, distribution, numbers"
            " FROM submissions WHERE disqualified = 0"
        ):
            subs.setdefault(row["field_id"], set()).add(
                (row["search_mode"], row["distribution"], row["numbers"])
            )
    finally:
        conn.close()
    ledger = [
        [
            f["range_start"],
            f["range_end"],
            min(f["check_level"], 1),
            sorted(subs.get(f["id"], set())),
        ]
        for f in fields
    ]
    return hashlib.sha256(
        json.dumps(ledger, sort_keys=True).encode()
    ).hexdigest()


def _exactly_once_violations(db_path: str) -> int:
    conn = sqlite3.connect(db_path)
    try:
        return conn.execute(
            "SELECT COUNT(*) FROM (SELECT submit_id FROM submissions"
            " WHERE submit_id IS NOT NULL GROUP BY submit_id"
            " HAVING COUNT(*) > 1)"
        ).fetchone()[0]
    finally:
        conn.close()


def _forgery_audit(db_path: str, forger_token: str) -> dict:
    conn = sqlite3.connect(db_path)
    try:
        total, disq = conn.execute(
            "SELECT COUNT(*), COALESCE(SUM(disqualified), 0)"
            " FROM submissions WHERE username = 'forger'"
        ).fetchone()
        canon = conn.execute(
            "SELECT COUNT(*) FROM fields f JOIN submissions s"
            " ON f.canon_submission_id = s.id WHERE s.username = 'forger'"
        ).fetchone()[0]
        suspect = conn.execute(
            "SELECT COALESCE(MAX(suspect), 0) FROM client_trust"
            " WHERE client_token = ?",
            (forger_token,),
        ).fetchone()[0]
    finally:
        conn.close()
    return {
        "forged_submissions": total,
        "forged_disqualified": disq,
        "forged_canon": canon,
        "forger_marked_suspect": bool(suspect),
    }


def _abandon_audit(db_path: str, abandoned: list[str]) -> dict:
    if not abandoned:
        return {"abandoned_fields": 0, "reissued_and_completed": 0}
    conn = sqlite3.connect(db_path)
    try:
        marks = ",".join("?" * len(abandoned))
        completed = conn.execute(
            f"SELECT COUNT(*) FROM fields f WHERE f.range_start IN ({marks})"
            " AND EXISTS (SELECT 1 FROM submissions s"
            "  WHERE s.field_id = f.id AND s.disqualified = 0"
            "  AND s.username != 'hoarder')",
            [f"{int(r):040d}" for r in abandoned],
        ).fetchone()[0]
    finally:
        conn.close()
    return {
        "abandoned_fields": len(abandoned),
        "reissued_and_completed": completed,
    }


# -- phases ------------------------------------------------------------------


async def _run_phase(cfg, db_path: str, adversarial: bool) -> dict:
    stats = Stats()
    stats.honest_429s = 0  # rate-limit hits against honest tokens only
    out: dict = {}
    tasks = [
        _honest_client(cfg, stats, i) for i in range(cfg["honest"])
    ]
    if adversarial:
        tasks += [
            _forger(cfg, out),
            _hoarder(cfg, out),
            _replayer(cfg, out),
            _flooder(cfg, out),
        ]
    t0 = time.monotonic()
    await asyncio.gather(*tasks)
    out["population_secs"] = round(time.monotonic() - t0, 2)
    out["drain_incomplete"] = await _drain(cfg, db_path)
    out["honest"] = {
        "clients": cfg["honest"],
        "fields_claimed": stats.fields_claimed,
        "submissions_accepted": stats.submissions_accepted,
        "duplicates": stats.duplicates,
        "item_errors": stats.http_errors,
        "rate_limited_429s": stats.honest_429s,
        "claim_p99_ms": _pctl(stats.claim_lat, 0.99),
        "submit_p50_ms": _pctl(stats.submit_lat, 0.50),
        "submit_p99_ms": _pctl(stats.submit_lat, 0.99),
    }
    return out


def run(
    *,
    honest: int = 16,
    rounds: int = 2,
    block_size: int = 6,
    target_fields: int = 600,
    forgeries: int = 10,
    detailed_forgeries: int = 4,
    flood_requests: int = 400,
    fault_spec: str | None = DEFAULT_FAULT_SPEC,
    fault_seed: int = DEFAULT_FAULT_SEED,
    run_label: str = "r01",
    keep_workdir: bool = False,
) -> dict:
    faults.configure(fault_spec, seed=fault_seed)
    workdir = tempfile.mkdtemp(prefix="adversarial-smoke-")
    phases: dict[str, dict] = {}
    digests: dict[str, str] = {}
    audits: dict[str, dict] = {}
    try:
        for phase in ("baseline", "adversarial"):
            db_path = os.path.join(workdir, f"{phase}.db")
            seeded = _seed_db(db_path, target_fields)
            server, port, logf = _spawn_server(db_path, workdir)
            try:
                cfg = {
                    "host": "127.0.0.1", "port": port,
                    "honest": honest, "rounds": rounds,
                    "block_size": block_size,
                    "forgeries": forgeries,
                    "detailed_forgeries": detailed_forgeries,
                    "flood_requests": flood_requests,
                }
                phases[phase] = asyncio.run(
                    _run_phase(cfg, db_path, phase == "adversarial")
                )
                phases[phase]["seeded_fields"] = seeded
            finally:
                server.terminate()
                try:
                    server.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    server.kill()
                    server.wait()
                logf.close()
            digests[phase] = _ledger_digest(db_path)
            audits[phase] = {
                "exactly_once_violations": _exactly_once_violations(db_path),
            }
            if phase == "adversarial":
                audits[phase].update(
                    _forgery_audit(
                        db_path, phases[phase].get("forger_token", "forger")
                    )
                )
                audits[phase].update(
                    _abandon_audit(
                        db_path, phases[phase].get("abandoned_fields", [])
                    )
                )
        adv = phases["adversarial"]
        adv_audit = audits["adversarial"]
        base_p99 = phases["baseline"]["honest"]["submit_p99_ms"]
        adv_p99 = adv["honest"]["submit_p99_ms"]
        assertions = {
            "forged_never_canon": adv_audit["forged_canon"] == 0,
            "forged_all_disqualified": (
                adv_audit["forged_submissions"] > 0
                and adv_audit["forged_disqualified"]
                == adv_audit["forged_submissions"]
            ),
            "forger_marked_suspect": adv_audit["forger_marked_suspect"],
            "abandoned_all_reissued_completed": (
                adv_audit["abandoned_fields"] > 0
                and adv_audit["reissued_and_completed"]
                == adv_audit["abandoned_fields"]
            ),
            "hoarder_hit_claim_cap": bool(adv.get("hoarder_hit_cap")),
            "flooder_rate_limited": adv.get("flood_429s", 0) > 0,
            "honest_zero_429s": (
                phases["baseline"]["honest"]["rate_limited_429s"] == 0
                and adv["honest"]["rate_limited_429s"] == 0
            ),
            "honest_p99_within_2x": (
                base_p99 > 0 and adv_p99 <= 2.0 * base_p99
            ),
            "replays_deduplicated": adv.get("replay_duplicates", 0) == 5,
            "exactly_once": all(
                a["exactly_once_violations"] == 0 for a in audits.values()
            ),
            "all_fields_completed": all(
                p["drain_incomplete"] == 0 for p in phases.values()
            ),
            "ledger_byte_identical": (
                digests["baseline"] == digests["adversarial"]
            ),
        }
        # The raw abandoned range list and the minted token are audit
        # detail, not report material.
        adv.pop("abandoned_fields", None)
        adv.pop("forger_token", None)
        return {
            "run": run_label,
            "base": BASE,
            "server_env": SERVER_ENV,
            "fault_spec": fault_spec,
            "fault_seed": fault_seed,
            "phases": phases,
            "audits": audits,
            "ledger_digests": digests,
            "honest_submit_p99_ms": {
                "baseline": base_p99,
                "adversarial": adv_p99,
                "ratio": round(adv_p99 / base_p99, 3) if base_p99 else None,
            },
            "assertions": assertions,
            "passed": all(assertions.values()),
        }
    finally:
        faults.configure(None)
        if not keep_workdir:
            import shutil

            shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="adversarial_smoke")
    p.add_argument("--honest", type=int, default=16)
    p.add_argument("--rounds", type=int, default=2)
    p.add_argument("--block-size", type=int, default=6)
    p.add_argument("--fields", type=int, default=600)
    p.add_argument("--forgeries", type=int, default=10)
    p.add_argument("--detailed-forgeries", type=int, default=4)
    p.add_argument("--flood-requests", type=int, default=400)
    p.add_argument("--fault-spec", default=DEFAULT_FAULT_SPEC)
    p.add_argument("--fault-seed", type=int, default=DEFAULT_FAULT_SEED)
    p.add_argument("--run-label", default="r01")
    p.add_argument("--out", default=None, help="write the JSON report here")
    args = p.parse_args(argv)
    report = run(
        honest=args.honest,
        rounds=args.rounds,
        block_size=args.block_size,
        target_fields=args.fields,
        forgeries=args.forgeries,
        detailed_forgeries=args.detailed_forgeries,
        flood_requests=args.flood_requests,
        fault_spec=args.fault_spec,
        fault_seed=args.fault_seed,
        run_label=args.run_label,
    )
    text = json.dumps(report, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
