#!/usr/bin/env python
"""Sweep kernel tuning knobs on the real chip and report throughput.

The analog of the reference's measured-sweep methodology (floor sweep
client_process_gpu.rs:85-94, prefilter gate :407-450): measure, don't guess.
Run on a TPU host; each configuration times a slice of the chosen benchmark
field after a same-shape warmup so compile time is excluded.

Usage:
    python scripts/tune_kernels.py detailed --mode extra-large \
        --slice 100000000 --batches 24,26,28
    python scripts/tune_kernels.py niceonly --mode extra-large \
        --slice 1000000000 --floors 65536,262144,1048576
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def time_detailed(data, batch_size: int, slice_size: int) -> float:
    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import engine

    warm = FieldSize(data.range_start, data.range_start + 1)
    engine.process_range_detailed(warm, data.base, backend="jax",
                                  batch_size=batch_size)
    rng = FieldSize(data.range_start, data.range_start + slice_size)
    t0 = time.monotonic()
    engine.process_range_detailed(rng, data.base, backend="jax",
                                  batch_size=batch_size)
    return time.monotonic() - t0


def time_niceonly(data, slice_size: int) -> float:
    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import engine

    warm = FieldSize(data.range_start, data.range_start + 1)
    engine.process_range_niceonly(warm, data.base, backend="jax",
                                  batch_size=1 << 20)
    rng = FieldSize(data.range_start, data.range_start + slice_size)
    t0 = time.monotonic()
    engine.process_range_niceonly(rng, data.base, backend="jax",
                                  batch_size=1 << 20)
    return time.monotonic() - t0


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("kind", choices=["detailed", "niceonly"])
    p.add_argument("--mode", default="extra-large")
    p.add_argument("--slice", type=int, default=100_000_000)
    p.add_argument("--batches", default="22,24,26,28",
                   help="log2 batch sizes to sweep (detailed)")
    p.add_argument("--floors", default="65536,262144,1048576",
                   help="MSD floors to sweep (niceonly; pins via env)")
    args = p.parse_args()

    # Make JAX_PLATFORMS authoritative (some PJRT plugins override the env
    # var at import time; see nice_tpu/utils/platform.py).
    platform = os.environ.get("JAX_PLATFORMS")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    from nice_tpu.core.benchmark import BenchmarkMode, get_benchmark_field

    data = get_benchmark_field(BenchmarkMode(args.mode))
    print(f"{args.kind} {args.mode}: base {data.base}, slice {args.slice:.0e}")

    if args.kind == "detailed":
        for shift in (int(s) for s in args.batches.split(",")):
            el = time_detailed(data, 1 << shift, args.slice)
            print(
                f"  batch 2^{shift}: {el:8.3f}s  "
                f"{args.slice / el / 1e6:10.1f} M n/s"
            )
    else:
        from nice_tpu.ops import adaptive_floor

        for floor in (int(f) for f in args.floors.split(",")):
            os.environ["NICE_TPU_MSD_FLOOR"] = str(floor)
            adaptive_floor.reset_for_tests()  # re-read the pin
            el = time_niceonly(data, args.slice)
            print(
                f"  floor {floor:>8}: {el:8.3f}s  "
                f"{args.slice / el / 1e6:10.1f} M n/s"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
