#!/usr/bin/env python
"""Sweep kernel tuning knobs on the real chip and report throughput.

The analog of the reference's measured-sweep methodology (floor sweep
client_process_gpu.rs:85-94, prefilter gate :407-450): measure, don't guess.
Run on a TPU host; each configuration times a slice of the chosen benchmark
field after a same-shape warmup so compile time is excluded.

The detailed/niceonly kinds sweep the cartesian grid of --batches x
--sweep-rows x --carry, pinning block_rows / carry_interval through the same
NICE_TPU_* env vars the engine's autotune precedence honors (env > tuned >
default, ops/autotune.py) — so the sweep times exactly the dispatch path a
pinned production run would take. --json emits one machine-readable line per
configuration; ops/autotune.sweep() runs this script that way and persists
the best-throughput config as the (mode, base, backend) winner.

Usage:
    python scripts/tune_kernels.py detailed --mode extra-large \
        --slice 100000000 --batches 24,26,28
    python scripts/tune_kernels.py detailed --mode hi-base --backend pallas \
        --batches 24,26 --sweep-rows 64,128,256 --carry 0,2,4 --json
    python scripts/tune_kernels.py niceonly --mode extra-large \
        --slice 1000000000 --floors 65536,262144,1048576
    python scripts/tune_kernels.py blocks --mode extra-large
    python scripts/tune_kernels.py stride-blocks --mode massive
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def time_detailed(data, batch_size: int, slice_size: int,
                  backend: str = "jax") -> float:
    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import engine

    warm = FieldSize(data.range_start, data.range_start + 1)
    engine.process_range_detailed(warm, data.base, backend=backend,
                                  batch_size=batch_size)
    rng = FieldSize(data.range_start, data.range_start + slice_size)
    t0 = time.monotonic()
    engine.process_range_detailed(rng, data.base, backend=backend,
                                  batch_size=batch_size)
    return time.monotonic() - t0


def time_niceonly(data, slice_size: int, batch_size: int = 1 << 20,
                  backend: str = "jax") -> float:
    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import engine

    warm = FieldSize(data.range_start, data.range_start + 1)
    engine.process_range_niceonly(warm, data.base, backend=backend,
                                  batch_size=batch_size)
    rng = FieldSize(data.range_start, data.range_start + slice_size)
    t0 = time.monotonic()
    engine.process_range_niceonly(rng, data.base, backend=backend,
                                  batch_size=batch_size)
    return time.monotonic() - t0


def sweep_stats_blocks(data, rows_list, batch_shift: int) -> None:
    """Raw stats-kernel lanes/s per block_rows (source of the committed
    BLOCK_ROWS sweep in ops/pallas_engine.py)."""
    import numpy as np

    from nice_tpu.core import base_range
    from nice_tpu.ops import pallas_engine as pe
    from nice_tpu.ops.limbs import get_plan, int_to_limbs

    plan = get_plan(data.base)
    br = base_range.get_base_range(data.base)
    start = int_to_limbs(br[0] + 1000, plan.limbs_n)
    batch = 1 << batch_shift
    for rows in rows_list:
        # detailed_batch clamps to a block that tiles the batch exactly;
        # report the EFFECTIVE rows so the sweep never labels a
        # configuration that did not run. (No cache_clear needed:
        # block_rows is part of the callable's cache key.)
        eff = pe._effective_block_rows(batch, rows)
        h, _ = pe.detailed_batch(plan, batch, start, np.int32(batch),
                                 block_rows=rows)
        np.asarray(h)
        t0 = time.monotonic()
        reps = 5
        for _ in range(reps):
            h, _ = pe.detailed_batch(plan, batch, start, np.int32(batch),
                                     block_rows=rows)
        np.asarray(h)
        el = (time.monotonic() - t0) / reps
        print(f"  stats block_rows={eff:4d}: {el*1e3:7.1f} ms = "
              f"{batch/el/1e9:.2f} G lanes/s")


def sweep_stride_blocks(data, rows_list) -> None:
    """Raw strided-kernel lanes/s per _STRIDED_BLOCK_ROWS_MAX (source of the
    committed sweep in ops/pallas_engine.py). Uses the field's planned
    (k, periods) at the current floor on a full descriptor group."""
    import numpy as np

    from nice_tpu.core import base_range
    from nice_tpu.ops import engine, pallas_engine as pe
    from nice_tpu.ops.limbs import get_plan, int_to_limbs

    base = data.base
    plan = get_plan(base)
    s = engine._strided_setup(base, data.range_size)
    if s is None:
        print("  strided path unavailable for this base")
        return
    spec, periods = s.spec, s.periods
    span = periods * spec.modulus
    br = base_range.get_base_range(base)
    lo = br[0] + 1000
    packed = np.zeros((1024, 12), dtype=np.uint32)
    for i in range(1024):
        n0 = (lo // spec.modulus) * spec.modulus + i * span
        packed[i, 0:4] = int_to_limbs(n0, 4)
        packed[i, 4:8] = int_to_limbs(lo, 4)
        packed[i, 8:12] = int_to_limbs(lo + 1024 * span, 4)
    lanes = 1024 * periods * spec.num_residues
    saved = pe._STRIDED_BLOCK_ROWS_MAX
    try:
        for rows in rows_list:
            pe._STRIDED_BLOCK_ROWS_MAX = rows
            pe._strided_callable.cache_clear()
            run = pe._strided_callable(plan, spec, 1024, periods)
            np.asarray(run(packed, np.int32(1024)))
            t0 = time.monotonic()
            reps = 10
            for _ in range(reps):
                r = run(packed, np.int32(1024))
            np.asarray(r)
            el = (time.monotonic() - t0) / reps
            print(f"  stride block_rows_max={rows:4d} (k={s.k} p={periods}): "
                  f"{el*1e3:7.1f} ms/group = {lanes/el/1e9:.2f} G lanes/s")
    finally:
        pe._STRIDED_BLOCK_ROWS_MAX = saved
        pe._strided_callable.cache_clear()


def _pin_env(name: str, value: int | None) -> None:
    """Pin (or clear) one NICE_TPU_* knob for the next timed config."""
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = str(value)


def _emit(as_json: bool, human: str, rec: dict) -> None:
    if as_json:
        print(json.dumps(rec), flush=True)
    else:
        print(human, flush=True)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "kind", choices=["detailed", "niceonly", "blocks", "stride-blocks"]
    )
    p.add_argument("--mode", default="extra-large")
    p.add_argument("--backend", default="jax",
                   choices=["jax", "jnp", "pallas"],
                   help="engine backend to time (jax auto-selects Pallas on "
                   "TPU; pallas demands the Pallas kernels or fails)")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object per timed configuration "
                   "(consumed by ops/autotune.sweep)")
    p.add_argument("--slice", type=int, default=100_000_000)
    p.add_argument("--batches", default=None,
                   help="log2 batch sizes to sweep (default 22,24,26,28 for "
                   "detailed, 20 for niceonly); the blocks sweep uses "
                   "--block-batch instead")
    p.add_argument("--sweep-rows", default="",
                   help="block_rows values to sweep per batch "
                   "(detailed/niceonly; pins NICE_TPU_BLOCK_ROWS per config; "
                   "empty = engine default)")
    p.add_argument("--carry", default="0",
                   help="carry-save resolution intervals to sweep "
                   "(pins NICE_TPU_CARRY_INTERVAL per config; 0 = resolve "
                   "carries once at the end)")
    p.add_argument("--mxu", default="auto", choices=["auto", "on", "off"],
                   help="limb-multiply engine axis: auto sweeps both the VPU "
                   "carry-save path and the MXU dot_general path (pins "
                   "NICE_TPU_MXU per config); on/off pins one of them")
    p.add_argument("--megaloop", default="",
                   help="megaloop segment lengths to sweep (pins "
                   "NICE_TPU_MEGALOOP_SEGMENT per config; 1 = per-batch feed "
                   "loop; empty = engine default)")
    p.add_argument("--block-batch", type=int, default=26,
                   help="log2 batch for the blocks sweep (26 matches the "
                   "committed BLOCK_ROWS sweep in ops/pallas_engine.py)")
    p.add_argument("--floors", default="65536,262144,1048576",
                   help="MSD floors to sweep (niceonly; pins via env)")
    p.add_argument("--rows", default="32,64,128,256,512",
                   help="block rows to sweep (blocks / stride-blocks)")
    args = p.parse_args()

    # Make JAX_PLATFORMS authoritative (some PJRT plugins override the env
    # var at import time; see nice_tpu/utils/platform.py).
    platform = os.environ.get("JAX_PLATFORMS")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    from nice_tpu.core.benchmark import BenchmarkMode, get_benchmark_field

    data = get_benchmark_field(BenchmarkMode(args.mode))
    if not args.json:
        print(f"{args.kind} {args.mode}: base {data.base}, "
              f"slice {args.slice:.0e}")

    if args.batches is None:
        args.batches = "22,24,26,28" if args.kind == "detailed" else "20"
    shifts = [int(s) for s in args.batches.split(",")]
    rows_sweep = [int(r) for r in args.sweep_rows.split(",")] \
        if args.sweep_rows else [None]
    carries = [int(c) for c in args.carry.split(",")]
    mxu_sweep = {"auto": [0, 1], "on": [1], "off": [0]}[args.mxu]
    mega_sweep = [int(m) for m in args.megaloop.split(",")] \
        if args.megaloop else [None]

    def rec_for(batch_size, rows, carry, floor, el, use_mxu=None,
                megaloop=None):
        rec = {
            "kind": args.kind, "mode": args.mode, "base": data.base,
            "backend": args.backend, "batch_size": batch_size,
            "block_rows": rows, "carry_interval": carry,
            "use_mxu": use_mxu, "megaloop": megaloop,
            "msd_floor": floor, "elapsed_secs": round(el, 6),
            "numbers_per_sec": round(args.slice / el, 1) if el > 0 else None,
        }
        # With NICE_TPU_STEPPROF=1 the engine left the most recent field's
        # phase attribution behind; autotune.record persists it with the
        # winner so regressions are attributable to a phase.
        from nice_tpu.obs import stepprof

        if stepprof.LAST_BREAKDOWN:
            rec["phase_breakdown"] = {
                p: round(float(stepprof.LAST_BREAKDOWN.get(p, 0.0)), 6)
                for p in stepprof.PHASES
            }
        return rec

    if args.kind == "blocks":
        sweep_stats_blocks(
            data, [int(r) for r in args.rows.split(",")], args.block_batch
        )
    elif args.kind == "stride-blocks":
        sweep_stride_blocks(data, [int(r) for r in args.rows.split(",")])
    elif args.kind == "detailed":
        for shift, rows, carry, use_mxu, mega in itertools.product(
                shifts, rows_sweep, carries, mxu_sweep, mega_sweep):
            _pin_env("NICE_TPU_BLOCK_ROWS", rows)
            _pin_env("NICE_TPU_CARRY_INTERVAL", carry)
            _pin_env("NICE_TPU_MXU", use_mxu)
            _pin_env("NICE_TPU_MEGALOOP_SEGMENT", mega)
            el = time_detailed(data, 1 << shift, args.slice, args.backend)
            _emit(
                args.json,
                f"  batch 2^{shift} rows {rows or 'def'} carry {carry} "
                f"mxu {use_mxu} mega {mega or 'def'}: "
                f"{el:8.3f}s  {args.slice / el / 1e6:10.1f} M n/s",
                rec_for(1 << shift, rows, carry, None, el, use_mxu, mega),
            )
    else:
        from nice_tpu.ops import adaptive_floor

        for floor in (int(f) for f in args.floors.split(",")):
            os.environ["NICE_TPU_MSD_FLOOR"] = str(floor)
            adaptive_floor.reset_for_tests()  # re-read the pin
            for shift, carry, use_mxu, mega in itertools.product(
                    shifts, carries, mxu_sweep, mega_sweep):
                _pin_env("NICE_TPU_CARRY_INTERVAL", carry)
                _pin_env("NICE_TPU_MXU", use_mxu)
                _pin_env("NICE_TPU_MEGALOOP_SEGMENT", mega)
                el = time_niceonly(data, args.slice, 1 << shift, args.backend)
                _emit(
                    args.json,
                    f"  floor {floor:>8} batch 2^{shift} carry {carry} "
                    f"mxu {use_mxu} mega {mega or 'def'}: "
                    f"{el:8.3f}s  {args.slice / el / 1e6:10.1f} M n/s",
                    rec_for(1 << shift, None, carry, floor, el, use_mxu,
                            mega),
                )
    return 0


if __name__ == "__main__":
    sys.exit(main())
