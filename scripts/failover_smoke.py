"""Failover drill: replicated ledger + epoch-fenced hot-standby promotion.

Runs a REAL primary + hot standby server pair (separate subprocesses,
separate sqlite files, replication over HTTP) and real block-lease clients
configured with BOTH endpoints (--api-base "primary,standby"), under a
pinned fault schedule:

  * clients: http.submit_block / http.submit drop_response@0.4 — accepted
    submits whose 200 the client never sees, forcing exactly-once replays;
  * standby: repl.stream conn_error@0.15 — the op-log pull loses its
    connection mid-stream and must resume from its applied cursor.

Mid-run, once client run 2 holds its block lease and the standby's
applied_seq has caught the primary's op log, the primary is SIGKILLed and
the standby is promoted (POST /repl/promote). The in-flight client must
re-route to the promoted standby and land its submits there; later runs
claim from the promoted ledger directly.

  asserts:
    * every client run exits 0 across the failover;
    * the promoted ledger holds EXACTLY one accepted submission per field,
      each byte-identical to a fault-free scalar recomputation — dropped
      responses, replication, and promotion never double- or un-counted;
    * every field's journal timeline on the promoted ledger is gap-free
      (per-field seq contiguous from 1) with exactly one submit_accepted —
      replicated pre-failover events and locally-written post-promotion
      events stitched into one timeline;
    * the resurrected old primary is FENCED: a write stamped with the
      promoted epoch gets 410, and so does a later unstamped write
      (sticky) — split-brain double-canonicalization is structurally off;
    * the faults demonstrably fired (drops, duplicate replays, repl.stream
      errors, client endpoint rotation).

Prints ONE JSON line and writes it to <workdir>/failover.json. Usage:

    python scripts/failover_smoke.py [workdir]
"""

import glob
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE = 22  # full valid range [234256, 656395)
FIELD_SIZE = 75_000  # -> 6 fields over the base range
BLOCK = 2  # fields per claim_block lease -> 3 client runs cover the base
CLIENT_FAULTS = (
    # @1: the FIRST submit of every client run loses its response — the
    # server accepted, the client must replay, deterministically each run.
    "http.submit_block:drop_response@1,"
    "http.submit:drop_response@1"
)
STANDBY_FAULTS = "repl.stream:conn_error@0.15"
FAULT_SEED = "7"  # pinned: same drops / stream cuts every run
RUN_TIMEOUT = 300
POLL_SECS = 0.05
REPL_POLL_SECS = "0.05"


def _pick_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _start_server(db_path, port, log_path, standby_of=None, advertise=None,
                  extra_env=None):
    logf = open(log_path, "ab")
    cmd = [
        sys.executable, "-m", "nice_tpu.server",
        "--db", db_path, "--host", "127.0.0.1", "--port", str(port),
    ]
    if standby_of:
        cmd += ["--standby-of", standby_of]
    if advertise:
        cmd += ["--advertise", advertise]
    env = dict(os.environ, NICE_TPU_REPL_POLL_SECS=REPL_POLL_SECS)
    env.update(extra_env or {})
    proc = subprocess.Popen(cmd, stdout=logf, stderr=subprocess.STDOUT,
                            env=env)
    return proc, logf


def _wait_listening(port, proc, timeout=30) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return False
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return True
        except OSError:
            time.sleep(POLL_SECS)
    return False


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _post_json(url, body=None, headers=None, timeout=10):
    data = json.dumps(body or {}).encode()
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=data, headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _status_code(fn) -> int:
    """HTTP status of a urllib call expected to fail (0 = no HTTP error)."""
    try:
        fn()
        return 0
    except urllib.error.HTTPError as e:
        return e.code
    except urllib.error.URLError:
        return -1


def main() -> int:
    t_start = time.monotonic()
    if len(sys.argv) > 1:
        workdir = sys.argv[1]
        os.makedirs(workdir, exist_ok=True)
        cleanup = False
    else:
        workdir = tempfile.mkdtemp(prefix="failover-smoke-")
        cleanup = True
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import scalar
    from nice_tpu.server.db import Db

    p_db = os.path.join(workdir, "primary.db")
    s_db = os.path.join(workdir, "standby.db")
    ckpt_dir = os.path.join(workdir, "ckpt")
    db = Db(p_db)
    db.seed_base(BASE, field_size=FIELD_SIZE)
    fields = db.get_fields_in_base(BASE)
    db.close()

    # Fault-free canonical results, computed before any chaos runs.
    canon = {
        f.field_id: scalar.process_range_detailed(
            FieldSize(f.range_start, f.range_end), BASE
        )
        for f in fields
    }

    p_port, s_port = _pick_port(), _pick_port()
    purl = f"http://127.0.0.1:{p_port}"
    surl = f"http://127.0.0.1:{s_port}"
    api_base = f"{purl},{surl}"

    failures = []
    line = {"workdir": workdir, "fields": len(fields)}

    primary, p_logf = _start_server(
        p_db, p_port, os.path.join(workdir, "primary.log"), advertise=purl
    )
    if not _wait_listening(p_port, primary):
        print(json.dumps({"ok": False, "workdir": workdir,
                          "failures": ["primary never listened"]}), flush=True)
        return 1
    standby, s_logf = _start_server(
        s_db, s_port, os.path.join(workdir, "standby.log"),
        standby_of=purl, advertise=surl,
        extra_env={"NICE_TPU_FAULTS": STANDBY_FAULTS,
                   "NICE_TPU_FAULTS_SEED": FAULT_SEED},
    )
    if not _wait_listening(s_port, standby):
        print(json.dumps({"ok": False, "workdir": workdir,
                          "failures": ["standby never listened"]}), flush=True)
        return 1

    client_env = dict(
        os.environ,
        NICE_TPU_FAULTS=CLIENT_FAULTS,
        NICE_TPU_FAULTS_SEED=FAULT_SEED,
        NICE_TPU_CLAIM_BLOCK=str(BLOCK),
    )
    client_cmd = [
        sys.executable, "-m", "nice_tpu.client", "detailed",
        "--api-base", api_base,
        "--backend", "jnp",
        "--batch-size", "8192",
        "--checkpoint-dir", ckpt_dir,
        "--checkpoint-secs", "5",
        "--max-retries", "12",
        "--renew-secs", "5",
        "--username", "failover-smoke",
    ]

    def claims_count(path) -> int:
        d = Db(path)
        try:
            with d._read_conn() as conn:
                return conn.execute(
                    "SELECT COUNT(*) FROM claims"
                ).fetchone()[0]
        finally:
            d.close()

    def standby_caught_up() -> bool:
        try:
            target = _get_json(f"{purl}/status")["repl"]["seq"]
            applied = _get_json(f"{surl}/status")["repl"]["applied_seq"]
            return applied >= target
        except Exception:
            return False

    run_logs = []
    for run in range(len(fields) // BLOCK):
        log_path = os.path.join(workdir, f"client-run{run + 1}.log")
        run_logs.append(log_path)
        with open(log_path, "wb") as logf:
            proc = subprocess.Popen(
                client_cmd, stdout=logf, stderr=subprocess.STDOUT,
                env=client_env,
            )
            if run == 1:
                # The failover: once run 2 holds its block lease (it is now
                # processing) and the standby has applied everything the
                # primary committed, SIGKILL the primary and promote. The
                # client's submit must re-route to the promoted standby.
                before = run * BLOCK
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    if (claims_count(p_db) > before
                            or proc.poll() is not None):
                        break
                    time.sleep(POLL_SECS)
                if claims_count(p_db) <= before:
                    failures.append(
                        "run 2 never claimed its block; failover skipped"
                    )
                else:
                    # The predicate is racy against live write traffic
                    # (the primary's seq keeps moving), so remember that
                    # it held once rather than re-evaluating at the end.
                    caught = False
                    deadline = time.monotonic() + 30
                    while time.monotonic() < deadline:
                        if standby_caught_up():
                            caught = True
                            break
                        time.sleep(POLL_SECS)
                    if not caught:
                        failures.append(
                            "standby never caught the primary op log"
                        )
                    primary.send_signal(signal.SIGKILL)
                    primary.wait()
                    p_logf.close()
                    line["primary_killed"] = True
                    try:
                        resp = _post_json(f"{surl}/repl/promote")
                        line["promoted_epoch"] = resp.get("epoch")
                    except Exception as e:  # noqa: BLE001
                        failures.append(f"promotion failed: {e}")
            try:
                rc = proc.wait(timeout=RUN_TIMEOUT)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                rc = -9
        if rc != 0:
            tail = open(log_path, errors="replace").read()[-2000:]
            failures.append(f"client run {run + 1} exited {rc}; tail: {tail}")

    logs_text = "".join(
        open(p, errors="replace").read() for p in run_logs
    )

    # Outage-spooled submissions deliver against the server list: the dead
    # primary rotates to the promoted standby, which dedupes by submit_id.
    spool_glob = os.path.join(ckpt_dir, "spool", "*.json")
    line["spooled"] = len(glob.glob(spool_glob))
    if glob.glob(spool_glob):
        from nice_tpu.faults.spool import SubmissionSpool

        SubmissionSpool(os.path.join(ckpt_dir, "spool")).replay(api_base)
    if glob.glob(spool_glob):
        failures.append("spooled submissions remained undeliverable")

    # -- exactly once, byte-identical, on the PROMOTED ledger ---------------
    db = Db(s_db)
    total_subs = 0
    for f in fields:
        subs = db.get_detailed_submissions_by_field(f.field_id)
        total_subs += len(subs)
        if len(subs) != 1:
            failures.append(
                f"field {f.field_id} has {len(subs)} accepted submissions "
                "on the promoted ledger, expected exactly 1"
            )
            continue
        sub, ref = subs[0], canon[f.field_id]
        got_dist = {d.num_uniques: d.count for d in sub.distribution}
        ref_dist = {d.num_uniques: d.count for d in ref.distribution}
        if got_dist != ref_dist:
            failures.append(
                f"field {f.field_id}: distribution != fault-free scalar run"
            )
        got_nums = {(n.number, n.num_uniques) for n in sub.numbers}
        ref_nums = {(n.number, n.num_uniques) for n in ref.nice_numbers}
        if got_nums != ref_nums:
            failures.append(
                f"field {f.field_id}: nice numbers != fault-free scalar run"
            )
    line["submissions"] = total_subs

    # -- gap-free journal timelines across the promotion --------------------
    accepted_events = 0
    with db._read_conn() as conn:
        for f in fields:
            rows = conn.execute(
                "SELECT seq, kind FROM field_events WHERE field_id = ?"
                " ORDER BY seq", (f.field_id,),
            ).fetchall()
            seqs = [r[0] for r in rows]
            if seqs != list(range(1, len(seqs) + 1)):
                failures.append(
                    f"field {f.field_id} journal timeline has gaps: {seqs}"
                )
            kinds = [r[1] for r in rows]
            n_accept = kinds.count("submit_accepted")
            accepted_events += n_accept
            if n_accept != 1:
                failures.append(
                    f"field {f.field_id} timeline has {n_accept}"
                    f" submit_accepted events, expected 1: {kinds}"
                )
    db.close()
    line["accepted_events"] = accepted_events

    # -- the resurrected old primary is fenced ------------------------------
    epoch = line.get("promoted_epoch") or 2
    primary, p_logf = _start_server(
        p_db, p_port, os.path.join(workdir, "primary.log")
    )
    if not _wait_listening(p_port, primary):
        failures.append("old primary did not resurrect")
    else:
        stamped = _status_code(lambda: _post_json(
            f"{purl}/renew_claim", {"claim_id": 1},
            headers={"X-Nice-Epoch": str(epoch)},
        ))
        unstamped = _status_code(lambda: _post_json(
            f"{purl}/renew_claim", {"claim_id": 1},
        ))
        line["fence_stamped_status"] = stamped
        line["fence_unstamped_status"] = unstamped
        if stamped != 410:
            failures.append(
                f"stamped write to resurrected primary got {stamped},"
                " expected 410 (epoch fence)"
            )
        if unstamped != 410:
            failures.append(
                f"unstamped write after fencing got {unstamped},"
                " expected sticky 410"
            )

    # -- the faults demonstrably fired --------------------------------------
    standby_log = open(
        os.path.join(workdir, "standby.log"), errors="replace"
    ).read()
    line["dropped_responses"] = logs_text.count("response dropped")
    if line["dropped_responses"] < 1:
        failures.append("no submit response was dropped (fault never fired)")
    if ("was a duplicate" not in logs_text
            and "were duplicates" not in logs_text):
        failures.append(
            "no duplicate-submit replay observed (exactly-once path unused)"
        )
    line["failovers"] = logs_text.count("rotating to next endpoint")
    if line["failovers"] < 1:
        failures.append("no client endpoint rotation observed")
    line["repl_stream_faults"] = standby_log.count(
        "injected repl.stream fault"
    )
    if line["repl_stream_faults"] < 1:
        failures.append("no repl.stream fault fired on the standby")

    for proc, logf in ((primary, p_logf), (standby, s_logf)):
        if proc.poll() is None:
            proc.terminate()
            proc.wait()
        logf.close()
    line["ok"] = not failures
    if failures:
        line["failures"] = failures
    line["elapsed_secs"] = round(time.monotonic() - t_start, 2)
    out = json.dumps(line)
    with open(os.path.join(workdir, "failover.json"), "w") as f:
        f.write(out + "\n")
    print(out, flush=True)
    if cleanup and not failures:
        shutil.rmtree(workdir, ignore_errors=True)
    return 0 if not failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
