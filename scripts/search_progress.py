#!/usr/bin/env python
"""Overall search progress per base from the coordination ledger (reference
scripts/search_progress.rs): fraction of fields at each check level.

Usage: python scripts/search_progress.py --db nice.db
"""

import argparse
import sys
from collections import Counter
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu.server.db import Db  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--db", default="nice.db")
    args = p.parse_args()
    db = Db(args.db)
    try:
        for base in db.get_bases():
            fields = db.get_fields_in_base(base)
            total = len(fields)
            by_cl = Counter(f.check_level for f in fields)
            size_total = sum(f.range_size for f in fields)
            size_checked = sum(f.range_size for f in fields if f.check_level >= 1)
            size_detailed = sum(f.range_size for f in fields if f.check_level >= 2)
            print(
                f"base {base}: {total} fields, "
                f"{100 * size_checked / size_total:.1f}% checked, "
                f"{100 * size_detailed / size_total:.1f}% detailed; "
                f"check levels {dict(sorted(by_cl.items()))}"
            )
    finally:
        db.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
