#!/usr/bin/env python
"""Overall search progress per base from the coordination ledger (reference
scripts/search_progress.rs): fraction of fields at each check level, plus a
per-(tenant, base) rollup when multi-tenant claims exist — interleaved tenant
submissions group under their own line instead of blending into the base
totals.

Usage: python scripts/search_progress.py --db nice.db
"""

import argparse
import sys
from collections import Counter
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu.server.db import Db  # noqa: E402


def tenant_lines(db: Db) -> list[str]:
    """One line per (tenant, mode, base) from the claims ledger."""
    out = []
    for row in db.tenant_rollup():
        out.append(
            f"tenant {row['tenant']} [{row['mode']} base {row['base']}]: "
            f"{row['claims']} claims, {row['submissions']} submissions"
        )
    return out


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--db", default="nice.db")
    args = p.parse_args()
    db = Db(args.db)
    try:
        for base in db.get_bases():
            fields = db.get_fields_in_base(base)
            total = len(fields)
            by_cl = Counter(f.check_level for f in fields)
            size_total = sum(f.range_size for f in fields)
            size_checked = sum(f.range_size for f in fields if f.check_level >= 1)
            size_detailed = sum(f.range_size for f in fields if f.check_level >= 2)
            print(
                f"base {base}: {total} fields, "
                f"{100 * size_checked / size_total:.1f}% checked, "
                f"{100 * size_detailed / size_total:.1f}% detailed; "
                f"check levels {dict(sorted(by_cl.items()))}"
            )
        lines = tenant_lines(db)
        if lines:
            print("-- tenants --")
            for line in lines:
                print(line)
    finally:
        db.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
