"""Chaos smoke check for the hardened submission path.

Runs a real server subprocess and real client subprocesses under a
deterministic fault schedule (NICE_TPU_FAULTS) and a genuine mid-run server
SIGKILL + restart, then asserts the ledger came out exactly right anyway:

  Clients run on the BLOCK-LEASE path (NICE_TPU_CLAIM_BLOCK=2): one
  /claim_block hands each run two fields under one lease and one
  /submit_block lands both results, so the chaos rides the batched
  coordination tier, not the per-field compatibility path.

  fault schedule (seed pinned so every run injects the same faults):
    * http.submit_block:drop_response@0.4 (plus http.submit for any spooled
      per-field replays) — the server processes the submit but the client
      sees a network error and retries, forcing the exactly-once submit_id
      replay path for every member of the block;
    * engine.dispatch:raise@batch=2 — one injected dispatch failure per
      client run, forcing the jnp -> scalar mid-field backend fallback;
  plus: the server is SIGKILLed while client run 2 is processing its field
  and restarted seconds later, so that run's submit retries ride through a
  real outage.

  asserts:
    * every client run exits 0;
    * every claimed field was accepted EXACTLY once (no double inserts from
      the dropped-response replays, no losses from the outage);
    * every submission is byte-identical to a fault-free scalar
      recomputation of its field (the fallback chain resumed, not restarted
      or skipped);
    * the duplicate-submit replay, the injected drop, and the backend
      downgrade are all visible in the logs (the faults actually fired).

Prints ONE JSON line. Usage:

    python scripts/chaos_smoke.py [workdir]
"""

import glob
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE = 22  # full valid range [234256, 656395)
FIELD_SIZE = 75_000  # -> 6 fields over the base range
BLOCK = 2  # fields per claim_block lease -> 3 client runs cover the base
FAULT_SPEC = (
    "http.submit_block:drop_response@0.4,"
    "http.submit:drop_response@0.4,"
    "engine.dispatch:raise@batch=2"
)
FAULT_SEED = "2"  # pinned: same drops every run; a later attempt delivers
RUN_TIMEOUT = 300
OUTAGE_SECS = 2.5
POLL_SECS = 0.05


def _pick_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _start_server(db_path: str, port: int, log_path: str):
    logf = open(log_path, "ab")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "nice_tpu.server",
            "--db", db_path, "--host", "127.0.0.1", "--port", str(port),
        ],
        stdout=logf, stderr=subprocess.STDOUT,
    )
    return proc, logf


def _wait_listening(port: int, proc, timeout: float = 30) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return False
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return True
        except OSError:
            time.sleep(POLL_SECS)
    return False


def main() -> int:
    t_start = time.monotonic()
    if len(sys.argv) > 1:
        workdir = sys.argv[1]
        os.makedirs(workdir, exist_ok=True)
        cleanup = False
    else:
        workdir = tempfile.mkdtemp(prefix="chaos-smoke-")
        cleanup = True
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import scalar
    from nice_tpu.server.db import Db

    db_path = os.path.join(workdir, "chaos.db")
    ckpt_dir = os.path.join(workdir, "ckpt")
    db = Db(db_path)
    db.seed_base(BASE, field_size=FIELD_SIZE)
    fields = db.get_fields_in_base(BASE)
    db.close()

    # Fault-free canonical results, computed before any chaos runs.
    canon = {
        f.field_id: scalar.process_range_detailed(
            FieldSize(f.range_start, f.range_end), BASE
        )
        for f in fields
    }

    port = _pick_port()
    api_base = f"http://127.0.0.1:{port}"
    server_log = os.path.join(workdir, "server.log")
    server, server_logf = _start_server(db_path, port, server_log)

    failures = []
    line = {"workdir": workdir, "fields": len(fields)}
    if not _wait_listening(port, server):
        print(json.dumps({"ok": False, "failures": ["server never listened"],
                          "workdir": workdir}), flush=True)
        return 1

    client_env = dict(
        os.environ,
        NICE_TPU_FAULTS=FAULT_SPEC,
        NICE_TPU_FAULTS_SEED=FAULT_SEED,
        NICE_TPU_CLAIM_BLOCK=str(BLOCK),
        # The fault schedule indexes per-BATCH dispatches (raise@batch=2);
        # the megaloop collapses a field below that index, so this drill
        # pins the per-batch feed loop. Fault handling under the megaloop
        # is covered by crash_resume_smoke --megaloop and test_megaloop.py.
        NICE_TPU_MEGALOOP="0",
    )
    client_cmd = [
        sys.executable, "-m", "nice_tpu.client", "detailed",
        "--api-base", api_base,
        "--backend", "jnp",
        "--batch-size", "8192",
        "--checkpoint-dir", ckpt_dir,
        "--checkpoint-secs", "5",
        "--max-retries", "12",
        "--renew-secs", "5",
        "--username", "chaos-smoke",
    ]

    def claims_count() -> int:
        d = Db(db_path)
        try:
            with d._read_conn() as conn:
                return conn.execute("SELECT COUNT(*) FROM claims").fetchone()[0]
        finally:
            d.close()

    run_logs = []
    for run in range(len(fields) // BLOCK):
        log_path = os.path.join(workdir, f"client-run{run + 1}.log")
        run_logs.append(log_path)
        with open(log_path, "wb") as logf:
            proc = subprocess.Popen(
                client_cmd, stdout=logf, stderr=subprocess.STDOUT,
                env=client_env,
            )
            if run == 1:
                # Mid-run chaos: once run 2's block claim has landed (it is
                # now processing), SIGKILL the server, hold a short outage,
                # and restart on the same port + DB. The WAL ledger must
                # survive the kill and the block submit must ride the retries.
                before = run * BLOCK  # claims minted per completed block run
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    if claims_count() > before or proc.poll() is not None:
                        break
                    time.sleep(POLL_SECS)
                if claims_count() > before:
                    server.send_signal(signal.SIGKILL)
                    server.wait()
                    server_logf.close()
                    line["server_killed"] = True
                    time.sleep(OUTAGE_SECS)
                    server, server_logf = _start_server(
                        db_path, port, server_log
                    )
                    if not _wait_listening(port, server):
                        failures.append("server did not come back after kill")
                else:
                    failures.append(
                        "run 2 never claimed its block; kill drill skipped"
                    )
            try:
                rc = proc.wait(timeout=RUN_TIMEOUT)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                rc = -9
        if rc != 0:
            tail = open(log_path, errors="replace").read()[-2000:]
            failures.append(f"client run {run + 1} exited {rc}; tail: {tail}")

    logs_text = "".join(
        open(p, errors="replace").read() for p in run_logs
    )

    # Any submission that had to be spooled (outage outlasting the retry
    # budget) is delivered by a replay pass; faults stay off here — this is
    # the recovery path, not another chaos run.
    spool_glob = os.path.join(ckpt_dir, "spool", "*.json")
    if glob.glob(spool_glob):
        from nice_tpu.faults.spool import SubmissionSpool

        SubmissionSpool(os.path.join(ckpt_dir, "spool")).replay(api_base)
    if glob.glob(spool_glob):
        failures.append("spooled submissions remained undeliverable")

    # -- exactly once, byte-identical --------------------------------------
    db = Db(db_path)
    total_subs = 0
    for f in fields:
        subs = db.get_detailed_submissions_by_field(f.field_id)
        total_subs += len(subs)
        if len(subs) != 1:
            failures.append(
                f"field {f.field_id} has {len(subs)} accepted submissions, "
                "expected exactly 1"
            )
            continue
        sub, ref = subs[0], canon[f.field_id]
        got_dist = {d.num_uniques: d.count for d in sub.distribution}
        ref_dist = {d.num_uniques: d.count for d in ref.distribution}
        if got_dist != ref_dist:
            failures.append(
                f"field {f.field_id}: distribution != fault-free scalar run"
            )
        got_nums = {(n.number, n.num_uniques) for n in sub.numbers}
        ref_nums = {(n.number, n.num_uniques) for n in ref.nice_numbers}
        if got_nums != ref_nums:
            failures.append(
                f"field {f.field_id}: nice numbers != fault-free scalar run"
            )
    db.close()
    line["submissions"] = total_subs

    # -- the faults demonstrably fired -------------------------------------
    line["dropped_responses"] = logs_text.count("response dropped")
    if line["dropped_responses"] < 1:
        failures.append("no submit response was dropped (fault never fired)")
    # Per-field replays log "was a duplicate"; block replays log
    # "... were duplicates". Either proves the exactly-once path ran.
    if ("was a duplicate" not in logs_text
            and "were duplicates" not in logs_text):
        failures.append(
            "no duplicate-submit replay observed (exactly-once path unused)"
        )
    line["dispatch_faults"] = logs_text.count("injected engine.dispatch fault")
    if line["dispatch_faults"] < 1:
        failures.append("no engine dispatch fault fired")
    if "failed mid-field" not in logs_text:
        failures.append("no backend downgrade observed after dispatch fault")

    server.terminate()
    server.wait()
    server_logf.close()
    line["ok"] = not failures
    if failures:
        line["failures"] = failures
    line["elapsed_secs"] = round(time.monotonic() - t_start, 2)
    print(json.dumps(line), flush=True)
    if cleanup and not failures:
        shutil.rmtree(workdir, ignore_errors=True)
    return 0 if not failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
